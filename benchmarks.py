"""Five-config BASELINE benchmark matrix (BASELINE.md; VERDICT r1 next-step 2).

Runs the reference's five acceptance configurations and records BOTH metric
axes for each:

  1. SingleTrainer — MNIST MLP              (reference: examples/mnist.py)
  2. DOWNPOUR      — MNIST CNN, 8 workers
  3. AEASGD        — ATLAS-Higgs classifier (reference: examples/workflow.ipynb)
  4. ADAG          — CIFAR-10 CNN
  5. DynSGD        — ResNet-18, ImageNet-shaped

Axes: steady-state **samples/sec/chip** (per-worker window timings with each
worker's first, compile-bearing window dropped) and **epochs-to-target-
accuracy** (1-epoch rounds until the held-out accuracy crosses the config's
target). Configs 1-5 run the synthetic stand-ins (BASELINE.md records
`published: {}` — nothing real was downloadable), so their accuracy axis is
comparable across rounds of THIS framework, not against upstream numbers.
Config 6 runs the REAL handwritten-digit set shipped in-repo
(distkeras_tpu/data/digits.csv via load_csv + the native parser), so its
accuracy axis is measured against real-world data.

Writes BENCHMARKS.json and BENCHMARKS.md at the repo root:

    python benchmarks.py [--configs 1,2,3,4,5,6] [--scale smoke|full] [--cpu]

Backend selection mirrors bench.py: probe out-of-process, fall back to an
8-virtual-device CPU mesh when no accelerator answers.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def resolve_platform(force_cpu: bool) -> str:
    from bench import setup_backend
    from distkeras_tpu.utils.compile_cache import enable_compile_cache

    platform = setup_backend(cpu=force_cpu, cpu_devices=8)
    if force_cpu:
        return platform
    enable_compile_cache(platform=platform)
    if platform == "cpu":
        # no accelerator: widen to the 8-device virtual mesh so the
        # multi-worker configs actually exercise their sharding
        from distkeras_tpu.parallel.mesh import force_cpu_mesh

        force_cpu_mesh(8)
    return platform


def steady_samples_per_sec(history) -> float:
    """Aggregate steady-state throughput: per worker, drop the first window
    (it carries the XLA compile) and sum samples/seconds; workers run
    concurrently, so their rates add. Datasets so small that a worker's
    epoch fits in ONE window (config 7's 569 real rows) would measure 0
    after the drop — fall back to the all-windows rate there (marked by
    the caller's row being dominated by compile, which the per-epoch
    loop's later rounds amortize)."""
    total = 0.0
    for wid in sorted(history._windows):
        timings = history._windows[wid][1:]
        if not timings:
            timings = history._windows[wid]
        secs = sum(dt for _, dt in timings)
        if secs > 0:
            total += sum(s for s, _ in timings) / secs
    return total


def run_config(cfg, scale, platform):
    import jax

    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.predictors import ModelPredictor

    print(f"== config {cfg['id']}: {cfg['name']}")
    train, test, label_col, pred_cols = cfg["data"](scale)
    model = cfg["model"](scale)
    rounds = cfg["max_epochs"][scale]
    target = cfg["target"][scale]

    curve = []
    elapsed = 0.0
    sps_rounds = []
    epochs_to_target = None
    for r in range(rounds):
        trainer = cfg["trainer"](model, scale, label_col)
        # per-round seed: each 1-epoch round must see a fresh shuffle order
        # (a fixed seed would replay the identical order every round)
        trainer.seed = trainer.seed + r
        t0 = time.perf_counter()
        model = trainer.train(train, shuffle=True)
        elapsed += time.perf_counter() - t0
        sps_rounds.append(steady_samples_per_sec(trainer.history))

        pred = ModelPredictor(model, batch_size=256).predict(test)
        for t in pred_cols:
            pred = t(pred)
        acc = AccuracyEvaluator(
            label_col="label",
            **({"prediction_col": "prediction_index"} if pred_cols else {}),
        ).evaluate(pred)
        curve.append({"epoch": r + 1, "seconds": round(elapsed, 2), "accuracy": acc})
        print(f"   epoch {r + 1}: t={elapsed:.1f}s acc={acc:.4f}", flush=True)
        if epochs_to_target is None and acc >= target:
            epochs_to_target = r + 1
            break

    n_chips = len(jax.devices()) if platform != "cpu" else 1
    best_sps = max(sps_rounds)
    return {
        "config": cfg["id"],
        "name": cfg["name"],
        "trainer": cfg["trainer_name"],
        "model": cfg["model_name"],
        "scale": scale,
        "samples_per_sec_per_chip": round(best_sps / max(n_chips, 1), 1),
        "target_accuracy": target,
        "epochs_to_target": epochs_to_target,
        "final_accuracy": curve[-1]["accuracy"],
        "train_rows": len(train),
        "seconds_total": round(elapsed, 1),
        "curve": curve,
    }


def build_configs(platform):
    from distkeras_tpu import (
        ADAG,
        AEASGD,
        DOWNPOUR,
        DynSGD,
        LabelIndexTransformer,
        MinMaxTransformer,
        OneHotTransformer,
        SingleTrainer,
    )
    from distkeras_tpu.data import loaders
    from distkeras_tpu.models import zoo

    def mnist_data(flat):
        def make(scale):
            n = 8192 if scale == "full" else 2048
            # hardened r4 (VERDICT r3 weak #6): 4-prototype mixture per
            # class + 10% resampled labels -> Bayes ceiling ~0.91 — the
            # epochs-to-target axis discriminates instead of saturating
            # at 1.0000. SPATIAL patterns (like real MNIST, and like the
            # CIFAR config): the iid-pixel variant is adversarial to
            # conv weight sharing — the CNN config sat at chance for 6
            # epochs on it while spatial tasks learn healthily
            ds = loaders.synthetic_mnist(
                n=n, seed=0, flat=flat, spatial=True,
                protos_per_class=4, label_noise=0.1, noise=1.2,
            )
            ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
            ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
            train, test = ds.split(0.9, seed=7)
            return train, test, "label_onehot", []

        return make

    def higgs_data(scale):
        n = 16384 if scale == "full" else 4096
        ds = loaders.synthetic_higgs(n=n, seed=1)
        ds = OneHotTransformer(2, output_col="label_onehot").transform(ds)
        train, test = ds.split(0.9, seed=7)
        return train, test, "label_onehot", []

    def cifar_data(scale):
        n = 8192 if scale == "full" else 2048
        # hardened r4: 3-pattern mixture + 10% label noise (see mnist_data)
        ds = loaders.synthetic_cifar10(
            n=n, seed=2, protos_per_class=3, label_noise=0.1,
        )
        ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
        ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
        train, test = ds.split(0.9, seed=7)
        return train, test, "label_onehot", []

    def digits_data(scale):
        ds = loaders.digits()
        ds = MinMaxTransformer(0, 1, o_min=0, o_max=16).transform(ds)
        ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
        train, test = ds.split(0.85, seed=7)
        return train, test, "label_onehot", []

    def breast_cancer_data(scale):
        from distkeras_tpu import StandardScaleTransformer

        # REAL tabular data at both scales (569 rows are what they are).
        # Split BEFORE fitting the scaler: held-out statistics must not
        # shape the normalization the accuracy axis is judged under.
        train, test = loaders.breast_cancer().split(0.85, seed=7)
        scaler = StandardScaleTransformer().fit(train)
        onehot = OneHotTransformer(2, output_col="label_onehot")
        train = onehot.transform(scaler.transform(train))
        test = onehot.transform(scaler.transform(test))
        return train, test, "label_onehot", []

    def imagenet_data(scale):
        from distkeras_tpu import LabelIndexTransformer

        n = 4096 if scale == "full" else 768
        # smoke keeps the model/image shape but 10 classes: 768 rows over
        # 100 classes is ~7 samples/class — data-starved regardless of
        # trainer (r2 calibration: acc plateaued at ~2x chance)
        classes = 100 if scale == "full" else 10
        size = 64
        # 10% label noise for the <1.0 ceiling (VERDICT r3 task 4); the
        # class count already keeps this config data-starved at smoke
        ds = loaders.synthetic_imagenet(
            n=n, num_classes=classes, size=size, seed=3, label_noise=0.1,
        )
        ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
        ds = OneHotTransformer(classes, output_col="label_onehot").transform(ds)
        train, test = ds.split(0.9, seed=7)
        return train, test, "label_onehot", [LabelIndexTransformer(classes)]

    common = dict(loss="categorical_crossentropy", seed=0)
    # simulated mode: the deterministic seeded interleaving of worker
    # begins/finishes. Thread mode's staleness profile depends on host core
    # count (a 1-core host starves workers into divergence), which would
    # make the accuracy axis measure the benchmark machine, not the
    # algorithm; the simulator bounds staleness the way a real per-chip
    # deployment does and is reproducible across rounds.
    dist = dict(common, communication_window=4, mode="simulated")
    # bf16 is the TPU compute dtype; XLA CPU emulates it slowly, so the CPU
    # fallback measures in f32
    dtype = None if platform == "cpu" else "bfloat16"

    return [
        {
            "id": 1,
            "name": "SingleTrainer / MNIST MLP",
            "trainer_name": "SingleTrainer",
            "model_name": "mnist_mlp",
            "data": mnist_data(flat=True),
            "model": lambda scale: zoo.mnist_mlp(seed=0),
            "trainer": lambda m, scale, lc: SingleTrainer(
                m, "sgd", learning_rate=0.05, batch_size=64,
                num_epoch=1, label_col=lc, **common,
            ),
            # ceiling ~0.91 under the hardened generator (r4): targets sit
            # a learnable margin below it; r4 CPU calibration on the
            # spatial task (noise 1.2): .34/.32/.43/.74/.72/.80/.71/.84
            "target": {"smoke": 0.78, "full": 0.82},
            "max_epochs": {"smoke": 10, "full": 10},
        },
        {
            "id": 2,
            "name": "DOWNPOUR / MNIST CNN / 8 workers",
            "trainer_name": "DOWNPOUR",
            "model_name": "mnist_cnn",
            "data": mnist_data(flat=False),
            "model": lambda scale: zoo.mnist_cnn(seed=0),
            # 8 workers' window deltas sum at the PS -> local adam lr
            # scaled down from 1e-3 (r2: full lr oscillates). r4: the
            # hardened mixture task needs more signal than the r2 easy
            # task — lr/8 (1.25e-4) sat at chance for 6 of 8 epochs
            # (0.29 @ epoch 8, still rising); 2.5e-4 = lr/4 is the
            # recalibrated point
            "trainer": lambda m, scale, lc: DOWNPOUR(
                m, "adam", learning_rate=2.5e-4, batch_size=32, num_epoch=1,
                num_workers=8, label_col=lc,
                compute_dtype=dtype, **dist,
            ),
            # hardened-generator ceiling ~0.91; async learns slower than
            # the single trainer, so the target sits lower still
            "target": {"smoke": 0.75, "full": 0.80},
            "max_epochs": {"smoke": 12, "full": 12},
        },
        {
            "id": 3,
            "name": "AEASGD / ATLAS-Higgs MLP",
            "trainer_name": "AEASGD",
            "model_name": "higgs_mlp",
            "data": higgs_data,
            "model": lambda scale: zoo.higgs_mlp(seed=0),
            "trainer": lambda m, scale, lc: AEASGD(
                m, "sgd", learning_rate=0.02, rho=10.0, batch_size=64,
                num_epoch=1, num_workers=4, label_col=lc, **dist,
            ),
            "target": {"smoke": 0.85, "full": 0.85},
            "max_epochs": {"smoke": 6, "full": 12},
        },
        {
            "id": 4,
            "name": "ADAG / CIFAR-10 CNN",
            "trainer_name": "ADAG",
            "model_name": "cifar10_cnn",
            "data": cifar_data,
            # bn_momentum 0.9: smoke epochs are ~57 steps; the 0.99 default
            # leaves eval-mode BN stats stale for hundreds of steps, so
            # held-out accuracy lags training by epochs (r2 calibration)
            "model": lambda scale: zoo.cifar10_cnn(seed=0, bn_momentum=0.9),
            # sgd lr 0.05: the ADAG convergence calibration from
            # tests/test_trainers_async.py (async + adam is fragile — the
            # adaptive step does not shrink near the optimum)
            "trainer": lambda m, scale, lc: ADAG(
                m, "sgd", learning_rate=0.05, batch_size=32, num_epoch=1,
                num_workers=4, label_col=lc,
                compute_dtype=dtype, **dist,
            ),
            # hardened-generator ceiling ~0.91 (3-pattern mixture + 10%
            # label noise)
            "target": {"smoke": 0.70, "full": 0.78},
            "max_epochs": {"smoke": 8, "full": 10},
        },
        {
            "id": 5,
            "name": "DynSGD / ResNet-18 / ImageNet-shaped",
            "trainer_name": "DynSGD",
            "model_name": "resnet18",
            "data": imagenet_data,
            "model": lambda scale: zoo.resnet18(
                num_classes=100 if scale == "full" else 10,
                input_shape=(64, 64, 3), seed=0,
                bn_momentum=0.9,
            ),
            # adam lr 1e-3 (r2 calibration): a from-scratch ResNet needs
            # adam here — plain sgd at 0.02/0.1 left it at a constant
            # prediction, while single-trainer adam hits 1.0 by epoch 2.
            # No lr/num_workers division: DynSGD's 1/(staleness+1) scaling
            # already divides the summed deltas by ~num_workers under the
            # round-robin schedule.
            "trainer": lambda m, scale, lc: DynSGD(
                m, "adam", learning_rate=1e-3, batch_size=32, num_epoch=1,
                num_workers=4, label_col=lc,
                compute_dtype=dtype, **dist,
            ),
            # 10% label noise caps the ceiling ~0.90; smoke stays
            # data-starved (768 rows / 10 classes) so the bar is low
            "target": {"smoke": 0.45, "full": 0.60},
            "max_epochs": {"smoke": 8, "full": 8},
        },
        {
            "id": 6,
            "name": "SingleTrainer / REAL digits (in-repo CSV)",
            "trainer_name": "SingleTrainer",
            "model_name": "digits_mlp",
            # REAL data (VERDICT r2 missing #1): 1,797 8x8 handwritten
            # digits shipped in-repo, parsed through load_csv + the native
            # C++ reader — the one matrix row whose accuracy axis is
            # measured against data the builder did not design. Same rows
            # at both scales (the set is what it is).
            "data": digits_data,
            "model": lambda scale: zoo.digits_mlp(seed=0),
            "trainer": lambda m, scale, lc: SingleTrainer(
                m, "adam", learning_rate=1e-3, batch_size=32,
                num_epoch=1, label_col=lc, **common,
            ),
            "target": {"smoke": 0.93, "full": 0.95},
            "max_epochs": {"smoke": 15, "full": 30},
        },
        {
            "id": 7,
            "name": "AEASGD / REAL breast-cancer (in-repo CSV)",
            "trainer_name": "AEASGD",
            "model_name": "higgs_mlp",
            # REAL tabular data (VERDICT r3 missing #1): the 569-row
            # Wisconsin diagnostic set shipped in-repo — the real
            # counterpart of config 3's ATLAS-Higgs-shaped task (30
            # features, binary target, reference: examples/workflow.ipynb)
            # giving the async-PS family a row measured against data the
            # builder did not design. Ceiling ~0.97 (real-data Bayes
            # floor); r4 CPU calibration (leak-free scaler): .884/.942.
            "data": breast_cancer_data,
            "model": lambda scale: zoo.higgs_mlp(seed=0),
            "trainer": lambda m, scale, lc: AEASGD(
                m, "sgd", learning_rate=0.02, rho=10.0, batch_size=32,
                num_epoch=1, num_workers=4, label_col=lc, **dist,
            ),
            "target": {"smoke": 0.93, "full": 0.93},
            "max_epochs": {"smoke": 8, "full": 8},
        },
    ]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2,3,4,5,6,7")
    ap.add_argument("--scale", choices=["smoke", "full"], default="smoke")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument(
        "--cpu-full", action="store_true",
        help="allow --scale full on the CPU fallback (VERDICT r3 weak #6: "
        "an unintended full-scale CPU pass burned 73 min on one config; "
        "full scale on CPU must be asked for, not stumbled into)",
    )
    ap.add_argument("--out", default=".")
    args = ap.parse_args()

    platform = resolve_platform(args.cpu)
    if platform == "cpu" and args.scale == "full" and not args.cpu_full:
        print("scale 'full' on the CPU fallback downgraded to 'smoke' "
              "(pass --cpu-full to force; see --help)")
        args.scale = "smoke"
    import jax

    device_kind = jax.devices()[0].device_kind
    print(f"platform: {platform} ({device_kind}), scale: {args.scale}")

    want = {int(c) for c in args.configs.split(",")}
    rows = []
    for cfg in build_configs(platform):
        if cfg["id"] not in want:
            continue
        try:
            rows.append(run_config(cfg, args.scale, platform))
        except Exception as exc:  # one bad config must not lose the others
            print(f"   config {cfg['id']} FAILED: {exc}", flush=True)
            rows.append(
                {
                    "config": cfg["id"],
                    "name": cfg["name"],
                    "error": f"{type(exc).__name__}: {exc}",
                }
            )
        # write after every config: a killed/timed-out run keeps its rows
        write_outputs(rows, platform, device_kind, args.scale, args.out)
    if rows:
        print("wrote BENCHMARKS.json / BENCHMARKS.md")
    else:
        print(f"no configs matched {sorted(want)}; nothing written")


def config_stamp(cfg_id: int) -> str:
    """PER-CONFIG calibration fingerprint: the source of ``build_configs``
    (trainer classes, lrs, batch sizes, targets) plus the specific loader
    and model-zoo functions THAT config calls (and, for the real-data
    config, the shipped csv bytes). Rows carry their config's stamp so a
    partial rerun after a calibration change cannot silently merge with
    rows measured under the old definitions (ADVICE r2 #2) — while edits
    scoped to one config (regenerating digits.csv, retuning one model)
    invalidate only that config's rows, never TPU measurements of the
    others that a CPU box cannot re-produce. Memoized: stamps cannot
    change mid-run."""
    import hashlib
    import inspect

    if not _CONFIG_STAMPS:
        from distkeras_tpu.data import loaders
        from distkeras_tpu.models import zoo

        synth = (
            loaders._prototype_classification,
            loaders._spatial_prototype_classification,
            loaders._coarse_grid,
            loaders._apply_label_noise,
        )
        sources = {
            1: synth + (loaders.synthetic_mnist, zoo.mnist_mlp),
            2: synth + (loaders.synthetic_mnist, zoo.mnist_cnn),
            3: synth + (loaders.synthetic_higgs, zoo.higgs_mlp),
            4: synth + (loaders.synthetic_cifar10, zoo.cifar10_cnn),
            5: synth
            + (loaders.synthetic_imagenet, zoo._basic_block, zoo.resnet18),
            6: (loaders.digits, loaders.load_csv, zoo.digits_mlp),
            7: (loaders.breast_cancer, loaders.load_csv, zoo.higgs_mlp),
        }
        data_dir = os.path.dirname(os.path.abspath(loaders.__file__))
        # the real configs' accuracy axes are DEFINED by the shipped
        # dataset bytes, not just the loader code
        real_csvs = {6: "digits.csv", 7: "breast_cancer.csv"}
        for cid, fns in sources.items():
            h = hashlib.sha256(inspect.getsource(build_configs).encode())
            for fn in fns:
                h.update(inspect.getsource(fn).encode())
            if cid in real_csvs:
                try:
                    with open(os.path.join(data_dir, real_csvs[cid]), "rb") as f:
                        h.update(f.read())
                except OSError:
                    h.update(real_csvs[cid].encode() + b"-missing")
            _CONFIG_STAMPS[cid] = h.hexdigest()[:12]
    # unknown config id (older/newer file formats): never matches
    return _CONFIG_STAMPS.get(int(cfg_id), "unknown-config")


_CONFIG_STAMPS = {}


def _merge_rows(fresh_rows, prior_rows):
    """Per-config merge: the fresh row wins, except a prior GOOD row beats a
    fresh ERROR row (a flaky rerun must not evict a valid measurement)."""
    prior_good = {r["config"]: r for r in prior_rows if "error" not in r}
    fresh = {
        r["config"]: (
            prior_good[r["config"]]
            if "error" in r and r["config"] in prior_good
            else r
        )
        for r in fresh_rows
    }
    return sorted(
        list(fresh.values())
        + [r for r in prior_rows if r["config"] not in fresh],
        key=lambda r: r["config"],
    )


def write_outputs(rows, platform, device_kind, scale, out):
    """Persist the matrix. BENCHMARKS.json holds one run section per
    (platform, scale) — a TPU harvest lands NEXT TO the CPU regression rows
    instead of clobbering them (VERDICT r2 task 8: both columns in the
    matrix). Within a section, a partial rerun (--configs 2) refreshes its
    rows without clobbering the others; a calibration change invalidates
    exactly the affected config's prior rows (per-row config stamps,
    ADVICE r2 #2)."""
    for r in rows:
        r.setdefault("stamp", config_stamp(r["config"]))
    path = os.path.join(out, "BENCHMARKS.json")
    runs = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                prior = json.load(f)
            if "runs" in prior:
                cand = list(prior["runs"])
            elif "results" in prior:  # one-run layout, the stamp's debut
                cand = [prior]
            else:
                cand = []
            # keep only well-formed sections (a malformed entry must
            # degrade to "overwrite", not crash the benchmark run), and
            # within each, only rows whose per-config stamp still matches
            # the current calibration — stampless or mismatched rows are
            # untrustworthy and drop; rows of OTHER configs survive
            dropped = 0
            for sec in cand:
                if not (
                    isinstance(sec, dict)
                    and all(
                        k in sec
                        for k in ("platform", "device_kind", "scale", "results")
                    )
                ):
                    continue
                kept = [
                    r
                    for r in sec["results"]
                    if isinstance(r, dict)
                    and r.get("stamp") == config_stamp(r.get("config", -1))
                ]
                dropped += len(sec["results"]) - len(kept)
                if kept:
                    runs.append(
                        {
                            "platform": sec["platform"],
                            "device_kind": sec["device_kind"],
                            "scale": sec["scale"],
                            "results": kept,
                        }
                    )
            if dropped:
                print(
                    f"dropped {dropped} prior BENCHMARKS row(s) whose "
                    "config stamp no longer matches the current calibration"
                )
        except (json.JSONDecodeError, KeyError, TypeError, AttributeError):
            pass  # unreadable prior file: overwrite it
    mine = {
        "platform": platform,
        "device_kind": device_kind,
        "scale": scale,
        "results": rows,
    }
    merged = False
    for i, run in enumerate(runs):
        if (
            run["platform"] == platform
            and run["device_kind"] == device_kind
            and run["scale"] == scale
        ):
            mine["results"] = _merge_rows(rows, run["results"])
            runs[i] = mine
            merged = True
            break
    if not merged:
        runs.append(mine)
    runs.sort(key=lambda r: (r["platform"] != "tpu", r["scale"]))

    os.makedirs(out, exist_ok=True)
    with open(os.path.join(out, "BENCHMARKS.json"), "w") as f:
        json.dump({"runs": runs}, f, indent=2)

    lines = [
        "# BASELINE benchmark matrix",
        "",
        "Configs 1-5 run hardened synthetic stand-ins — prototype "
        "mixtures + 10% resampled labels give a Bayes ceiling < 1.0, so "
        "the accuracy axis cannot saturate (BASELINE.md: `published: {}` "
        "— no upstream numbers exist); configs 6 and 7 run REAL in-repo "
        "CSVs (1,797-row digits, 569-row breast-cancer). Both BASELINE "
        "metric axes per config. "
        "samples/sec/chip is steady-state (compile window excluded). "
        "Rows carry per-config calibration stamps; rows from older "
        "calibrations are dropped automatically. "
        "Reproduce: `python benchmarks.py`.",
    ]
    for run in runs:
        lines += [
            "",
            f"## Platform `{run['platform']}` ({run['device_kind']}), "
            f"scale `{run['scale']}`",
            "",
            "| # | config | samples/sec/chip | target acc | epochs to target "
            "| final acc | total s |",
            "|---|---|---|---|---|---|---|",
        ]
        for r in run["results"]:
            if "error" in r:
                lines.append(
                    f"| {r['config']} | {r['name']} | error: {r['error']} "
                    "| | | | |"
                )
                continue
            ett = r["epochs_to_target"] if r["epochs_to_target"] else "not reached"
            lines.append(
                f"| {r['config']} | {r['name']} | {r['samples_per_sec_per_chip']} "
                f"| {r['target_accuracy']} | {ett} | {r['final_accuracy']:.4f} "
                f"| {r['seconds_total']} |"
            )
    with open(os.path.join(out, "BENCHMARKS.md"), "w") as f:
        f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
