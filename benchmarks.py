"""Five-config BASELINE benchmark matrix (BASELINE.md; VERDICT r1 next-step 2).

Runs the reference's five acceptance configurations and records BOTH metric
axes for each:

  1. SingleTrainer — MNIST MLP              (reference: examples/mnist.py)
  2. DOWNPOUR      — MNIST CNN, 8 workers
  3. AEASGD        — ATLAS-Higgs classifier (reference: examples/workflow.ipynb)
  4. ADAG          — CIFAR-10 CNN
  5. DynSGD        — ResNet-18, ImageNet-shaped

Axes: steady-state **samples/sec/chip** (per-worker window timings with each
worker's first, compile-bearing window dropped) and **epochs-to-target-
accuracy** (1-epoch rounds until the held-out accuracy crosses the config's
target). Configs 1-5 run the synthetic stand-ins (BASELINE.md records
`published: {}` — nothing real was downloadable), so their accuracy axis is
comparable across rounds of THIS framework, not against upstream numbers.
Config 6 runs the REAL handwritten-digit set shipped in-repo
(distkeras_tpu/data/digits.csv via load_csv + the native parser), so its
accuracy axis is measured against real-world data.

Writes BENCHMARKS.json and BENCHMARKS.md at the repo root:

    python benchmarks.py [--configs 1,2,3,4,5,6,7] [--scale smoke|full]
                         [--cpu] [--all]

By default only configs WITHOUT a current-calibration row for this
(platform, device, scale) are measured — a calibration edit re-measures
exactly the rows it invalidated (VERDICT r4 weak #5: a full CPU refresh
burns hours on this 1-core sandbox). ``--all`` (or an explicit --configs
list) forces re-measurement. Backend selection mirrors bench.py: probe
out-of-process, fall back to an 8-virtual-device CPU mesh when no
accelerator answers.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def resolve_platform(force_cpu: bool) -> str:
    from bench import setup_backend
    from distkeras_tpu.utils.compile_cache import enable_compile_cache

    platform = setup_backend(cpu=force_cpu, cpu_devices=8)
    if force_cpu:
        return platform
    enable_compile_cache(platform=platform)
    if platform == "cpu":
        # no accelerator: widen to the 8-device virtual mesh so the
        # multi-worker configs actually exercise their sharding
        from distkeras_tpu.parallel.mesh import force_cpu_mesh

        force_cpu_mesh(8)
    return platform


def steady_samples_per_sec(history):
    """Aggregate steady-state throughput: per worker, drop the first window
    (it carries the XLA compile) and sum samples/seconds; workers run
    concurrently, so their rates add. Datasets so small that a worker's
    epoch fits in ONE window (config 7's 569 real rows) would measure 0
    after the drop — fall back to the all-windows rate there. Returns
    ``(samples_per_sec, compile_in_window)``: the flag is True when any
    worker took the fallback, so the caller can mark the row as including
    compile time instead of silently contradicting the steady-state
    methodology (ADVICE r4 #2)."""
    total = 0.0
    fallback = False
    for wid in sorted(history._windows):
        timings = history._windows[wid][1:]
        if not timings:
            timings = history._windows[wid]
            fallback = True
        secs = sum(dt for _, dt in timings)
        if secs > 0:
            total += sum(s for s, _ in timings) / secs
    return total, fallback


def run_config(cfg, scale, platform):
    import jax

    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.predictors import ModelPredictor

    print(f"== config {cfg['id']}: {cfg['name']}")
    train, test, label_col, pred_cols = cfg["data"](scale)
    model = cfg["model"](scale)
    rounds = cfg["max_epochs"][scale]
    target = cfg["target"][scale]

    curve = []
    elapsed = 0.0
    sps_rounds = []
    epochs_to_target = None
    for r in range(rounds):
        trainer = cfg["trainer"](model, scale, label_col)
        # per-round seed: each 1-epoch round must see a fresh shuffle order
        # (a fixed seed would replay the identical order every round)
        trainer.seed = trainer.seed + r
        t0 = time.perf_counter()
        model = trainer.train(train, shuffle=True)
        elapsed += time.perf_counter() - t0
        sps_rounds.append(steady_samples_per_sec(trainer.history))

        pred = ModelPredictor(model, batch_size=256).predict(test)
        for t in pred_cols:
            pred = t(pred)
        acc = AccuracyEvaluator(
            label_col="label",
            **({"prediction_col": "prediction_index"} if pred_cols else {}),
        ).evaluate(pred)
        curve.append({"epoch": r + 1, "seconds": round(elapsed, 2), "accuracy": acc})
        print(f"   epoch {r + 1}: t={elapsed:.1f}s acc={acc:.4f}", flush=True)
        if epochs_to_target is None and acc >= target:
            epochs_to_target = r + 1
            break

    n_chips = len(jax.devices()) if platform != "cpu" else 1
    best_sps, compile_in_window = max(sps_rounds, key=lambda t: t[0])
    row = {
        "config": cfg["id"],
        "name": cfg["name"],
        "trainer": cfg["trainer_name"],
        "model": cfg["model_name"],
        "scale": scale,
        "samples_per_sec_per_chip": round(best_sps / max(n_chips, 1), 1),
        "target_accuracy": target,
        "epochs_to_target": epochs_to_target,
        "final_accuracy": curve[-1]["accuracy"],
        "train_rows": len(train),
        "seconds_total": round(elapsed, 1),
        "curve": curve,
    }
    if compile_in_window:
        row["compile_in_window"] = True
    return row


# ---------------------------------------------------------------------------
# Config definitions. ONE FUNCTION PER CONFIG: each config's calibration
# stamp hashes its own builder's source (plus its data helper and the
# loader/zoo functions it calls), so retuning one config invalidates only
# that config's rows — r4's single build_configs() hashed its whole source
# into every stamp, and a one-line target tweak silently deleted every TPU
# row in the matrix (VERDICT r4 weak #2 / task 8).
# ---------------------------------------------------------------------------


def _shared(platform):
    """Knobs every config shares; hashed into every stamp (editing them
    genuinely recalibrates the whole matrix)."""
    common = dict(loss="categorical_crossentropy", seed=0)
    # simulated mode: the deterministic seeded interleaving of worker
    # begins/finishes. Thread mode's staleness profile depends on host core
    # count (a 1-core host starves workers into divergence), which would
    # make the accuracy axis measure the benchmark machine, not the
    # algorithm; the simulator bounds staleness the way a real per-chip
    # deployment does and is reproducible across rounds.
    dist = dict(common, communication_window=4, mode="simulated")
    # bf16 is the TPU compute dtype; XLA CPU emulates it slowly, so the CPU
    # fallback measures in f32
    dtype = None if platform == "cpu" else "bfloat16"
    return common, dist, dtype


def _mnist_data(scale, flat):
    from distkeras_tpu import MinMaxTransformer, OneHotTransformer
    from distkeras_tpu.data import loaders

    n = 8192 if scale == "full" else 2048
    # hardened r4 (VERDICT r3 weak #6): 4-prototype mixture per class + 10%
    # resampled labels -> Bayes ceiling ~0.91 — the epochs-to-target axis
    # discriminates instead of saturating at 1.0000. SPATIAL patterns (like
    # real MNIST, and like the CIFAR config): the iid-pixel variant is
    # adversarial to conv weight sharing — the CNN config sat at chance for
    # 6 epochs on it while spatial tasks learn healthily
    ds = loaders.synthetic_mnist(
        n=n, seed=0, flat=flat, spatial=True,
        protos_per_class=4, label_noise=0.1, noise=1.2,
    )
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
    ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.9, seed=7)
    return train, test, "label_onehot", []


def _higgs_data(scale):
    from distkeras_tpu import OneHotTransformer
    from distkeras_tpu.data import loaders

    n = 16384 if scale == "full" else 4096
    ds = loaders.synthetic_higgs(n=n, seed=1)
    ds = OneHotTransformer(2, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.9, seed=7)
    return train, test, "label_onehot", []


def _cifar_data(scale):
    from distkeras_tpu import MinMaxTransformer, OneHotTransformer
    from distkeras_tpu.data import loaders

    n = 8192 if scale == "full" else 2048
    # hardened r4: 3-pattern mixture + 10% label noise (see _mnist_data)
    ds = loaders.synthetic_cifar10(
        n=n, seed=2, protos_per_class=3, label_noise=0.1,
    )
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
    ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.9, seed=7)
    return train, test, "label_onehot", []


def _digits_data(scale):
    from distkeras_tpu import MinMaxTransformer, OneHotTransformer
    from distkeras_tpu.data import loaders

    ds = loaders.digits()
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=16).transform(ds)
    ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.85, seed=7)
    return train, test, "label_onehot", []


def _breast_cancer_data(scale):
    from distkeras_tpu import OneHotTransformer, StandardScaleTransformer
    from distkeras_tpu.data import loaders

    # REAL tabular data at both scales (569 rows are what they are).
    # Split BEFORE fitting the scaler: held-out statistics must not
    # shape the normalization the accuracy axis is judged under.
    train, test = loaders.breast_cancer().split(0.85, seed=7)
    scaler = StandardScaleTransformer().fit(train)
    onehot = OneHotTransformer(2, output_col="label_onehot")
    train = onehot.transform(scaler.transform(train))
    test = onehot.transform(scaler.transform(test))
    return train, test, "label_onehot", []


def _imagenet_data(scale):
    from distkeras_tpu import (
        LabelIndexTransformer,
        MinMaxTransformer,
        OneHotTransformer,
    )
    from distkeras_tpu.data import loaders

    n = 4096 if scale == "full" else 768
    # smoke keeps the model/image shape but 10 classes: 768 rows over
    # 100 classes is ~7 samples/class — data-starved regardless of
    # trainer (r2 calibration: acc plateaued at ~2x chance)
    classes = 100 if scale == "full" else 10
    size = 64
    # 10% label noise for the <1.0 ceiling (VERDICT r3 task 4); the
    # class count already keeps this config data-starved at smoke
    ds = loaders.synthetic_imagenet(
        n=n, num_classes=classes, size=size, seed=3, label_noise=0.1,
    )
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
    ds = OneHotTransformer(classes, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.9, seed=7)
    return train, test, "label_onehot", [LabelIndexTransformer(classes)]


def _cfg1(platform):
    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.models import zoo

    common, _, _ = _shared(platform)
    return {
        "id": 1,
        "name": "SingleTrainer / MNIST MLP",
        "trainer_name": "SingleTrainer",
        "model_name": "mnist_mlp",
        "data": lambda scale: _mnist_data(scale, flat=True),
        "model": lambda scale: zoo.mnist_mlp(seed=0),
        "trainer": lambda m, scale, lc: SingleTrainer(
            m, "sgd", learning_rate=0.05, batch_size=64,
            num_epoch=1, label_col=lc, **common,
        ),
        # ceiling ~0.91 under the hardened generator (r4): targets sit
        # a learnable margin below it; r4 CPU calibration on the
        # spatial task (noise 1.2): .34/.32/.43/.74/.72/.80/.71/.84
        "target": {"smoke": 0.78, "full": 0.82},
        "max_epochs": {"smoke": 10, "full": 10},
    }


def _cfg2(platform):
    from distkeras_tpu import DOWNPOUR
    from distkeras_tpu.models import zoo

    _, dist, dtype = _shared(platform)
    return {
        "id": 2,
        "name": "DOWNPOUR / MNIST CNN / 8 workers",
        "trainer_name": "DOWNPOUR",
        "model_name": "mnist_cnn",
        "data": lambda scale: _mnist_data(scale, flat=False),
        # full-width CNN at BOTH scales: the r5 window-unroll fix
        # (workers._window_unroll — XLA:CPU ran conv windows inside while
        # loops ~33x slow) brought the full model's epoch from ~240 s back
        # under ~10 s on this sandbox, so the smoke row measures the REAL
        # BASELINE model again (r5 interim used width 0.5 to fit the
        # budget; zoo.mnist_cnn keeps the knob)
        "model": lambda scale: zoo.mnist_cnn(seed=0),
        # 8 workers' window deltas sum at the PS -> local adam lr
        # scaled down from 1e-3 (r2: full lr oscillates). r4: the
        # hardened mixture task needs more signal than the r2 easy
        # task — lr/8 (1.25e-4) sat at chance for 6 of 8 epochs
        # (0.29 @ epoch 8, still rising); 2.5e-4 = lr/4 is the
        # recalibrated point
        "trainer": lambda m, scale, lc: DOWNPOUR(
            m, "adam", learning_rate=2.5e-4, batch_size=32, num_epoch=1,
            num_workers=8, label_col=lc,
            compute_dtype=dtype, **dist,
        ),
        # hardened-generator ceiling ~0.91; async learns slower than
        # the single trainer, so the target sits lower still (r4
        # full-width calibration: hit .756 at epoch 6)
        "target": {"smoke": 0.75, "full": 0.80},
        "max_epochs": {"smoke": 12, "full": 12},
    }


def _cfg3(platform):
    from distkeras_tpu import AEASGD
    from distkeras_tpu.models import zoo

    _, dist, _ = _shared(platform)
    return {
        "id": 3,
        "name": "AEASGD / ATLAS-Higgs MLP",
        "trainer_name": "AEASGD",
        "model_name": "higgs_mlp",
        "data": _higgs_data,
        "model": lambda scale: zoo.higgs_mlp(seed=0),
        "trainer": lambda m, scale, lc: AEASGD(
            m, "sgd", learning_rate=0.02, rho=10.0, batch_size=64,
            num_epoch=1, num_workers=4, label_col=lc, **dist,
        ),
        "target": {"smoke": 0.85, "full": 0.85},
        "max_epochs": {"smoke": 6, "full": 12},
    }


def _cfg4(platform):
    from distkeras_tpu import ADAG
    from distkeras_tpu.models import zoo

    _, dist, dtype = _shared(platform)
    return {
        "id": 4,
        "name": "ADAG / CIFAR-10 CNN",
        "trainer_name": "ADAG",
        "model_name": "cifar10_cnn",
        "data": _cifar_data,
        # bn_momentum 0.9: smoke epochs are ~57 steps; the 0.99 default
        # leaves eval-mode BN stats stale for hundreds of steps, so
        # held-out accuracy lags training by epochs (r2 calibration).
        # Full width at both scales since the r5 window-unroll fix — see
        # _cfg2 (r4's 1,700 s/epoch was the XLA:CPU while-loop pathology)
        "model": lambda scale: zoo.cifar10_cnn(seed=0, bn_momentum=0.9),
        # sgd lr 0.05: the ADAG convergence calibration from
        # tests/test_trainers_async.py (async + adam is fragile — the
        # adaptive step does not shrink near the optimum)
        "trainer": lambda m, scale, lc: ADAG(
            m, "sgd", learning_rate=0.05, batch_size=32, num_epoch=1,
            num_workers=4, label_col=lc,
            compute_dtype=dtype, **dist,
        ),
        # hardened-generator ceiling ~0.91 (3-pattern mixture + 10%
        # label noise); r4 full-width calibration hit .70 at epoch 3
        "target": {"smoke": 0.70, "full": 0.78},
        "max_epochs": {"smoke": 8, "full": 10},
    }


def _cfg5(platform):
    from distkeras_tpu import DynSGD
    from distkeras_tpu.models import zoo

    _, dist, dtype = _shared(platform)
    return {
        "id": 5,
        "name": "DynSGD / ResNet-18 / ImageNet-shaped",
        "trainer_name": "DynSGD",
        "model_name": "resnet18",
        "data": _imagenet_data,
        # adam lr 1e-3 (r2 calibration): a from-scratch ResNet needs
        # adam here — plain sgd at 0.02/0.1 left it at a constant
        # prediction, while single-trainer adam hits 1.0 by epoch 2.
        # No lr/num_workers division: DynSGD's 1/(staleness+1) scaling
        # already divides the summed deltas by ~num_workers under the
        # round-robin schedule.
        # Full width at both scales since the r5 window-unroll fix — see
        # _cfg2 (r4's 430 s/epoch was the XLA:CPU while-loop pathology)
        "model": lambda scale: zoo.resnet18(
            num_classes=100 if scale == "full" else 10,
            input_shape=(64, 64, 3), seed=0,
            bn_momentum=0.9,
        ),
        "trainer": lambda m, scale, lc: DynSGD(
            m, "adam", learning_rate=1e-3, batch_size=32, num_epoch=1,
            num_workers=4, label_col=lc,
            compute_dtype=dtype, **dist,
        ),
        # 10% label noise caps the ceiling ~0.90; smoke stays
        # data-starved (768 rows / 10 classes) so the bar is low
        "target": {"smoke": 0.45, "full": 0.60},
        "max_epochs": {"smoke": 8, "full": 8},
    }


def _cfg6(platform):
    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.models import zoo

    common, _, _ = _shared(platform)
    return {
        "id": 6,
        "name": "SingleTrainer / REAL digits (in-repo CSV)",
        "trainer_name": "SingleTrainer",
        "model_name": "digits_mlp",
        # REAL data (VERDICT r2 missing #1): 1,797 8x8 handwritten
        # digits shipped in-repo, parsed through load_csv + the native
        # C++ reader — the one matrix row whose accuracy axis is
        # measured against data the builder did not design. Same rows
        # at both scales (the set is what it is).
        "data": _digits_data,
        "model": lambda scale: zoo.digits_mlp(seed=0),
        "trainer": lambda m, scale, lc: SingleTrainer(
            m, "adam", learning_rate=1e-3, batch_size=32,
            num_epoch=1, label_col=lc, **common,
        ),
        "target": {"smoke": 0.93, "full": 0.95},
        "max_epochs": {"smoke": 15, "full": 30},
    }


def _cfg7(platform):
    from distkeras_tpu import AEASGD
    from distkeras_tpu.models import zoo

    _, dist, _ = _shared(platform)
    return {
        "id": 7,
        "name": "AEASGD / REAL breast-cancer (in-repo CSV)",
        "trainer_name": "AEASGD",
        "model_name": "higgs_mlp",
        # REAL tabular data (VERDICT r3 missing #1): the 569-row
        # Wisconsin diagnostic set shipped in-repo — the real
        # counterpart of config 3's ATLAS-Higgs-shaped task (30
        # features, binary target, reference: examples/workflow.ipynb)
        # giving the async-PS family a row measured against data the
        # builder did not design. Ceiling ~0.97 (real-data Bayes
        # floor); r4 CPU calibration (leak-free scaler): .884/.942.
        "data": _breast_cancer_data,
        "model": lambda scale: zoo.higgs_mlp(seed=0),
        "trainer": lambda m, scale, lc: AEASGD(
            m, "sgd", learning_rate=0.02, rho=10.0, batch_size=32,
            num_epoch=1, num_workers=4, label_col=lc, **dist,
        ),
        # 0.87 sits at/below the WEAKER of the two committed calibration
        # runs (.884/.942) — the r4 target of 0.93 was above one of them,
        # i.e. seed-sensitive (ADVICE r4 #5)
        "target": {"smoke": 0.87, "full": 0.87},
        "max_epochs": {"smoke": 8, "full": 8},
    }


_CONFIG_BUILDERS = {
    1: _cfg1, 2: _cfg2, 3: _cfg3, 4: _cfg4, 5: _cfg5, 6: _cfg6, 7: _cfg7,
}


def build_configs(platform):
    return [_CONFIG_BUILDERS[i](platform) for i in sorted(_CONFIG_BUILDERS)]


def config_stamp(cfg_id: int) -> str:
    """PER-CONFIG calibration fingerprint: the source of THAT config's
    builder function, the shared-knob helper, the config's data helper, and
    the specific loader and model-zoo functions it calls (and, for the
    real-data configs, the shipped csv bytes). Rows carry their config's
    stamp so a partial rerun after a calibration change cannot silently
    merge with rows measured under the old definitions (ADVICE r2 #2) —
    while edits scoped to one config (regenerating digits.csv, retuning one
    model) invalidate only that config's rows, never TPU measurements of
    the others that a CPU box cannot re-produce. Memoized: stamps cannot
    change mid-run."""
    import hashlib
    import inspect

    if not _CONFIG_STAMPS:
        from distkeras_tpu.data import loaders
        from distkeras_tpu.models import zoo

        synth = (
            loaders._prototype_classification,
            loaders._spatial_prototype_classification,
            loaders._coarse_grid,
            loaders._apply_label_noise,
        )
        sources = {
            1: (_cfg1, _mnist_data) + synth
            + (loaders.synthetic_mnist, zoo.mnist_mlp),
            2: (_cfg2, _mnist_data) + synth
            + (loaders.synthetic_mnist, zoo._scaled, zoo.mnist_cnn),
            3: (_cfg3, _higgs_data) + synth
            + (loaders.synthetic_higgs, zoo.higgs_mlp),
            4: (_cfg4, _cifar_data) + synth
            + (loaders.synthetic_cifar10, zoo._scaled, zoo.cifar10_cnn),
            5: (_cfg5, _imagenet_data) + synth
            + (loaders.synthetic_imagenet, zoo._scaled, zoo._basic_block,
               zoo.resnet18),
            6: (_cfg6, _digits_data, loaders.digits, loaders.load_csv,
                zoo.digits_mlp),
            7: (_cfg7, _breast_cancer_data, loaders.breast_cancer,
                loaders.load_csv, zoo.higgs_mlp),
        }
        data_dir = os.path.dirname(os.path.abspath(loaders.__file__))
        # the real configs' accuracy axes are DEFINED by the shipped
        # dataset bytes, not just the loader code
        real_csvs = {6: "digits.csv", 7: "breast_cancer.csv"}
        for cid, fns in sources.items():
            h = hashlib.sha256(inspect.getsource(_shared).encode())
            for fn in fns:
                h.update(inspect.getsource(fn).encode())
            if cid in real_csvs:
                try:
                    with open(os.path.join(data_dir, real_csvs[cid]), "rb") as f:
                        h.update(f.read())
                except OSError:
                    h.update(real_csvs[cid].encode() + b"-missing")
            _CONFIG_STAMPS[cid] = h.hexdigest()[:12]
    # unknown/garbage config id (older/newer/hand-edited file formats):
    # never matches, never raises — one malformed row aborting the load
    # loop would silently delete every section after it, including the
    # chip evidence this machinery exists to preserve (r5 review finding)
    try:
        cid = int(cfg_id)
    except (TypeError, ValueError):
        return "unknown-config"
    return _CONFIG_STAMPS.get(cid, "unknown-config")


_CONFIG_STAMPS = {}


def _merge_rows(fresh_rows, prior_rows):
    """Per-config merge: the fresh row wins, except a prior GOOD row beats a
    fresh ERROR row (a flaky rerun must not evict a valid measurement)."""
    prior_good = {r["config"]: r for r in prior_rows if "error" not in r}
    fresh = {
        r["config"]: (
            prior_good[r["config"]]
            if "error" in r and r["config"] in prior_good
            else r
        )
        for r in fresh_rows
    }
    return sorted(
        list(fresh.values())
        + [r for r in prior_rows if r["config"] not in fresh],
        key=lambda r: r["config"],
    )


def load_prior_runs(path):
    """Read BENCHMARKS.json and re-validate every row against the CURRENT
    calibration stamps. Rows that still match stay in ``results``. CHIP rows
    that no longer match move to the section's ``stale_results`` instead of
    dropping — a calibration bump on a CPU-only sandbox must never delete
    the matrix's only TPU evidence (VERDICT r4 weak #2: r3's four chip rows
    vanished this way); they are retained, clearly labelled, until a fresh
    on-chip measurement of the same config supersedes them. Stale CPU rows
    still drop (this box can always re-measure them)."""
    runs = []
    dropped = 0
    if not os.path.exists(path):
        return runs, dropped
    try:
        with open(path) as f:
            prior = json.load(f)
        if "runs" in prior:
            cand = list(prior["runs"])
        elif "results" in prior:  # one-run layout, the stamp's debut
            cand = [prior]
        else:
            cand = []
        # keep only well-formed sections (a malformed entry must degrade to
        # "overwrite", not crash the benchmark run)
        for sec in cand:
            if not (
                isinstance(sec, dict)
                and all(
                    k in sec
                    for k in ("platform", "device_kind", "scale", "results")
                )
            ):
                continue
            is_chip = sec["platform"] != "cpu"
            # a stale row is only worth retaining if it can still render in
            # the evidence table — a hand-edited/truncated dict must not
            # crash every later run's render_md
            renderable = lambda r: isinstance(r, dict) and all(
                k in r
                for k in (
                    "config", "name", "samples_per_sec_per_chip",
                    "target_accuracy", "epochs_to_target",
                    "final_accuracy", "seconds_total",
                )
            )
            kept, stale = [], []
            for r in sec["results"]:
                if not isinstance(r, dict):
                    continue
                if r.get("stamp") == config_stamp(r.get("config", -1)):
                    kept.append(r)
                elif is_chip and "error" not in r and renderable(r):
                    stale.append(dict(r, stale_calibration=True))
                else:
                    dropped += 1
            if is_chip:
                for r in sec.get("stale_results", []):
                    if renderable(r):
                        stale.append(dict(r, stale_calibration=True))
            # a config measured under the current calibration no longer
            # needs its stale copy; dedupe stale copies per config (newest
            # first: fresh-section rows precede carried-over ones). Error
            # rows are NOT measurements — they must never evict the
            # last-known chip evidence they failed to replace
            fresh_ids = {
                r.get("config") for r in kept if "error" not in r
            }
            seen, deduped = set(), []
            for r in stale:
                cid = r.get("config")
                if cid in fresh_ids or cid in seen:
                    continue
                seen.add(cid)
                deduped.append(r)
            if kept or deduped:
                sec_out = {
                    "platform": sec["platform"],
                    "device_kind": sec["device_kind"],
                    "scale": sec["scale"],
                    "results": kept,
                }
                if deduped:
                    sec_out["stale_results"] = deduped
                runs.append(sec_out)
    except (json.JSONDecodeError, KeyError, TypeError, AttributeError):
        pass  # unreadable prior file: overwrite it
    return runs, dropped


def _north_star_cite(out):
    """Cite the committed chip-capability number from its artifact of
    record at render time (a hardcoded figure would silently go stale the
    next time a TPU window refreshes BENCH_TPU.json)."""
    try:
        with open(os.path.join(out, "BENCH_TPU.json")) as f:
            rec = json.load(f)
        return (
            f"`BENCH_TPU.json`: {rec['value']:,.0f} {rec['unit']}, "
            "same machine, same tunnel, batch "
            f"{rec.get('batch', 2048)} with an HBM-resident feed"
        )
    except (OSError, ValueError, KeyError):
        return "`BENCH_TPU.json` (device-resident feed, same machine)"


def render_md(runs, out):
    lines = [
        "# BASELINE benchmark matrix",
        "",
        "Configs 1-5 run hardened synthetic stand-ins — prototype "
        "mixtures + 10% resampled labels give a Bayes ceiling < 1.0, so "
        "the accuracy axis cannot saturate (BASELINE.md: `published: {}` "
        "— no upstream numbers exist); configs 6 and 7 run REAL in-repo "
        "CSVs (1,797-row digits, 569-row breast-cancer). Both BASELINE "
        "metric axes per config. "
        "samples/sec/chip is steady-state (compile window excluded); "
        "rows marked `*` had an epoch fit inside one timing window, so "
        "their rate could not exclude compile. "
        "Rows carry per-config calibration stamps; CPU rows from older "
        "calibrations are dropped automatically, while chip rows are "
        "retained in a labelled stale section until re-captured. "
        "Reproduce: `python benchmarks.py` (changed rows only; `--all` "
        "for a full refresh). "
        "CAVEAT on comparing the platform sections: smoke shapes are "
        "deliberately tiny, so per-window host-device dispatch dominates "
        "their wall clock. In this sandbox the TPU sits behind an `axon` "
        "network tunnel — every window round-trip pays WAN latency the "
        "local CPU rows never pay — so smoke-scale TPU rows can measure "
        "BELOW the CPU rows without saying anything about the chip. The "
        "matrix's job here is the accuracy axis (epochs-to-target, which "
        "is platform-honest) and cross-round regression; chip throughput "
        "capability is measured by the device-resident north-star "
        f"({_north_star_cite(out)}).",
    ]

    def table(rows):
        t = [
            "| # | config | samples/sec/chip | target acc | epochs to target "
            "| final acc | total s |",
            "|---|---|---|---|---|---|---|",
        ]
        for r in rows:
            if "error" in r:
                t.append(
                    f"| {r['config']} | {r['name']} | error: {r['error']} "
                    "| | | | |"
                )
                continue
            ett = r["epochs_to_target"] if r["epochs_to_target"] else "not reached"
            star = " \\*" if r.get("compile_in_window") else ""
            t.append(
                f"| {r['config']} | {r['name']} "
                f"| {r['samples_per_sec_per_chip']}{star} "
                f"| {r['target_accuracy']} | {ett} | {r['final_accuracy']:.4f} "
                f"| {r['seconds_total']} |"
            )
        return t

    for run in runs:
        lines += [
            "",
            f"## Platform `{run['platform']}` ({run['device_kind']}), "
            f"scale `{run['scale']}`",
            "",
        ]
        if run["results"]:
            lines += table(run["results"])
        if run.get("stale_results"):
            lines += [
                "",
                "### Stale calibration — retained as last-known chip evidence",
                "",
                "These rows were measured under an earlier calibration "
                "stamp; the current calibration has no on-chip replacement "
                "yet (captures queue in `tools/tpu_capture.sh` and land on "
                "the next healthy tunnel window). They are NOT comparable "
                "to current-calibration rows and are kept so the matrix "
                "never presents zero chip evidence.",
                "",
            ] + table(run["stale_results"])
    with open(os.path.join(out, "BENCHMARKS.md"), "w") as f:
        f.write("\n".join(lines) + "\n")


def write_outputs(rows, platform, device_kind, scale, out):
    """Persist the matrix. BENCHMARKS.json holds one run section per
    (platform, scale) — a TPU harvest lands NEXT TO the CPU regression rows
    instead of clobbering them (VERDICT r2 task 8: both columns in the
    matrix). Within a section, a partial rerun (--configs 2) refreshes its
    rows without clobbering the others; a calibration change invalidates
    exactly the affected config's prior rows (per-row config stamps,
    ADVICE r2 #2), with chip rows retained as labelled stale evidence
    (VERDICT r4 task 2)."""
    for r in rows:
        r.setdefault("stamp", config_stamp(r["config"]))
    path = os.path.join(out, "BENCHMARKS.json")
    runs, dropped = load_prior_runs(path)
    if dropped:
        print(
            f"dropped {dropped} prior BENCHMARKS row(s) whose "
            "config stamp no longer matches the current calibration"
        )
    mine = {
        "platform": platform,
        "device_kind": device_kind,
        "scale": scale,
        "results": rows,
    }
    merged = False
    for i, run in enumerate(runs):
        if (
            run["platform"] == platform
            and run["device_kind"] == device_kind
            and run["scale"] == scale
        ):
            mine["results"] = _merge_rows(rows, run["results"])
            fresh_ids = {
                r["config"] for r in mine["results"] if "error" not in r
            }
            carried = [
                r
                for r in run.get("stale_results", [])
                if r.get("config") not in fresh_ids
            ]
            if carried:
                mine["stale_results"] = carried
            runs[i] = mine
            merged = True
            break
    if not merged:
        runs.append(mine)
    runs.sort(key=lambda r: (r["platform"] != "tpu", r["scale"]))

    os.makedirs(out, exist_ok=True)
    with open(os.path.join(out, "BENCHMARKS.json"), "w") as f:
        json.dump({"runs": runs}, f, indent=2)
    render_md(runs, out)


def _current_configs(path, platform, device_kind, scale):
    """Config ids that already have a good, current-calibration row for this
    (platform, device, scale) section — the rows a default run may skip."""
    runs, _ = load_prior_runs(path)
    for run in runs:
        if (
            run["platform"] == platform
            and run["device_kind"] == device_kind
            and run["scale"] == scale
        ):
            return {
                r["config"] for r in run["results"] if "error" not in r
            }
    return set()


def main():
    ap = argparse.ArgumentParser()
    # None sentinel (not a default string): an EXPLICIT --configs list —
    # even the full "1,2,3,4,5,6,7" — must force re-measurement
    ap.add_argument("--configs", default=None)
    ap.add_argument("--scale", choices=["smoke", "full"], default="smoke")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument(
        "--all", action="store_true",
        help="re-measure configs whose rows already match the current "
        "calibration (default: skip them — a matrix refresh after a "
        "one-config retune must not re-burn hours on the others; "
        "VERDICT r4 weak #5)",
    )
    ap.add_argument(
        "--cpu-full", action="store_true",
        help="allow --scale full on the CPU fallback (VERDICT r3 weak #6: "
        "an unintended full-scale CPU pass burned 73 min on one config; "
        "full scale on CPU must be asked for, not stumbled into)",
    )
    ap.add_argument("--out", default=".")
    args = ap.parse_args()

    platform = resolve_platform(args.cpu)
    if platform == "cpu" and args.scale == "full" and not args.cpu_full:
        print("scale 'full' on the CPU fallback downgraded to 'smoke' "
              "(pass --cpu-full to force; see --help)")
        args.scale = "smoke"
    import jax

    device_kind = jax.devices()[0].device_kind
    print(f"platform: {platform} ({device_kind}), scale: {args.scale}")

    explicit = args.configs is not None
    want = {
        int(c)
        for c in (args.configs or "1,2,3,4,5,6,7").split(",")
    }
    if not args.all and not explicit:
        have = _current_configs(
            os.path.join(args.out, "BENCHMARKS.json"),
            platform, device_kind, args.scale,
        )
        skip = want & have
        if skip:
            print(
                f"skipping configs {sorted(skip)}: rows already current "
                "(--all or an explicit --configs list re-measures)"
            )
        want -= skip
    rows = []
    for cfg in build_configs(platform):
        if cfg["id"] not in want:
            continue
        try:
            rows.append(run_config(cfg, args.scale, platform))
        except Exception as exc:  # one bad config must not lose the others
            print(f"   config {cfg['id']} FAILED: {exc}", flush=True)
            rows.append(
                {
                    "config": cfg["id"],
                    "name": cfg["name"],
                    "error": f"{type(exc).__name__}: {exc}",
                }
            )
        # write after every config: a killed/timed-out run keeps its rows
        write_outputs(rows, platform, device_kind, args.scale, args.out)
    if rows:
        print("wrote BENCHMARKS.json / BENCHMARKS.md")
    elif not want and explicit is False and not args.all:
        print("all requested configs already have current rows; "
              "nothing re-measured (--all forces)")
    else:
        print(f"no configs matched {sorted(want)}; nothing written")


if __name__ == "__main__":
    main()
