"""Parameter servers for the asynchronous trainers.

Re-creation of the reference's PS runtime (reference:
distkeras/parameter_servers.py -> ParameterServer / SocketParameterServer /
DeltaParameterServer / ADAGParameterServer / DynSGDParameterServer) with the
same pull/commit verbs and per-algorithm commit rules, re-homed for TPU:

- The center variable is a host-resident pytree (numpy leaves — commits are
  in-place host adds, no device round-trip).
- In-process workers (threads driving per-chip windows) call ``pull`` /
  ``commit`` directly under a lock — the single-host fast path.
- ``SocketParameterServer`` serves the same PS object over TCP for
  cross-host (DCN) workers, with the reference's one-byte action protocol
  (b"p" pull, b"c" commit, b"s" stop) extended with b"a" (replica attach)
  and a one-byte reply status (b"k" ok / b"e" + typed error frame) so a
  protocol error can never silently desync the stream.

Replication & failover (no reference counterpart — upstream's PS death
kills the whole run):

- any ``ParameterServer`` can stream to warm standbys: ``attach_replica``
  hands the sink a consistent snapshot (center + meta + dedup table +
  worker snapshots) taken INSIDE the commit lock, then every post-dedup
  commit is forwarded in apply order over the same channel, semi-
  synchronously (the committer's ack implies the standby applied) — the
  standby's center, version counters, and exactly-once bookkeeping stay
  commit-identical to the primary's;
- ``SocketParameterServer(standby_of=(host, port))`` runs the standby
  side: sync on start, follow the replication stream, re-attach (fresh
  snapshot) if only the stream dies, and PROMOTE to primary when the
  primary itself is gone; while in standby role, client verbs are
  refused with a typed ``standby`` error;
- ``RemoteParameterServerClient`` accepts an endpoint list and fails
  over through ``networking.RetryPolicy``, transparently resending
  ``commit_id``-tagged commits — safe exactly-once, because the dedup
  table rode the replication stream.

Every commit rule is also exposed as a pure function
(``center', meta' = RULE(center, meta, delta, tag)``) so tests can assert
staleness/normalization semantics exactly (SURVEY §7.4).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time

logger = logging.getLogger(__name__)

import jax
import numpy as np

from distkeras_tpu import faults, networking
from distkeras_tpu.utils.serialization import (
    deserialize_params,
    pack_frame,
    serialize_params,
    unpack_frame,
)


def _to_host(tree):
    """Host numpy copies with float leaves normalized to float32.

    Integer/bool leaves keep their dtype: the compressed wire formats
    (int8 ``q`` trees, uint16 bf16 payloads, int32 top-k indices) must
    not be silently re-inflated to 4-byte floats — the old unconditional
    float32 coercion cost the remote-PS path most of its compression
    byte savings and turned top-k index arrays into floats (r4)."""

    def conv(a):
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
            return a
        return a.astype(np.float32, copy=False)

    return jax.tree.map(conv, tree)


# -------------------------------------------------------------- typed errors


class ParameterServerError(ConnectionError):
    """Typed PS protocol failure. Subclasses ``ConnectionError`` on
    purpose: every retry surface in the repo (``RetryPolicy.call``'s
    default ``retry_on``, the client's failover wrapper, worker retry)
    already treats connection errors as retriable, and every PS protocol
    error IS retriable — commits are exactly-once under resend by the
    dedup table, pulls are idempotent."""

    # a typed error FRAME arrived, so the connection is still framed
    # correctly: the client may retry in place without redialing.
    # Subclasses born from a dead/desynced stream override this.
    stream_in_sync = True

    def __init__(self, code: str, detail=None):
        msg = f"parameter server error: {code}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.code = code
        self.detail = detail


class StandbyError(ParameterServerError):
    """The dialed endpoint is a warm standby that has not (yet) promoted.
    Retriable by design: during a failover there is a window between the
    primary dying and the standby noticing; a policy-paced retry rides
    it out."""

    def __init__(self, detail=None):
        super().__init__("standby", detail)


class CommitNotAcknowledgedError(ParameterServerError):
    """A commit's ack never arrived (stream died, or the reply was not a
    valid status byte). Carries ``commit_id`` so the caller — and the
    log line a human reads at 3am — knows WHICH commit is in doubt; with
    a ``commit_id`` the resend is exactly-once (PS dedup), without one
    the caller must treat the commit as lost."""

    stream_in_sync = False  # the ack never framed: the stream is suspect

    def __init__(self, commit_id=None, detail=None):
        msg = f"commit {commit_id} not acknowledged"
        if detail:
            msg += f" ({detail})"
        ConnectionError.__init__(self, msg)
        self.code = "commit_not_acknowledged"
        self.detail = detail
        self.commit_id = commit_id


# ------------------------------------------------------- commit wire helpers
# One encoding of a commit (and one decoder) shared by the worker->PS path
# and the primary->standby replication stream, so the two cannot drift.


def _pack_commit(tree_delta, tag, commit_id, local_snap) -> bytes:
    header = {
        "tag": tag,
        "commit_id": list(commit_id) if commit_id is not None else None,
    }
    tree = tree_delta
    if local_snap is not None:
        # worker-local checkpoint state rides the same frame ("wrapped"
        # layout) so remote/DCN workers — and the standby's custody table
        # — keep full resume parity with in-process ones
        header["wrapped"] = True
        tree = {"delta": tree_delta, "snap": local_snap}
    return pack_frame(header, serialize_params(tree))


def _apply_commit_payload(ps: "ParameterServer", data: bytes,
                          _via: str = "client") -> None:
    header, blob = unpack_frame(data)
    commit_id = header.get("commit_id")
    if commit_id is not None:
        commit_id = (commit_id[0], commit_id[1])
    tree = deserialize_params(blob)
    local_snap = None
    if header.get("wrapped"):
        local_snap = tree.get("snap")
        tree = tree["delta"]
    ps.commit(
        tree,
        header.get("tag"),
        commit_id=commit_id,
        local_snap=local_snap,
        _via=_via,
    )


def _send_error(conn: socket.socket, code: str, **extra) -> None:
    """Typed error reply: status byte b"e" + an error frame. Best-effort —
    the peer may already be gone."""
    try:
        conn.sendall(b"e")
        networking.send_data(conn, pack_frame({"error": code, **extra}))
    except OSError:
        pass


# --------------------------------------------------------------------- rules
# Pure commit rules (testable without threads; reference: §4.2/§4.3 semantics)


def _wid_key(k):
    """Worker ids round-trip through JSON meta / str-keyed trees as strings;
    normalize back to int where possible."""
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def delta_rule(center, meta, delta, tag=None):
    """center += delta (DOWNPOUR / AEASGD / EAMSGD / ADAG commits)."""
    new_center = jax.tree.map(lambda c, d: c + np.asarray(d), center, delta)
    meta = dict(meta)
    meta["num_updates"] = meta.get("num_updates", 0) + 1
    return new_center, meta


def dynsgd_rule(center, meta, delta, tag):
    """Staleness-aware: center += delta / (staleness + 1).

    ``tag`` is the update counter the worker saw at pull time; staleness is
    how many commits landed since (reference: distkeras/parameter_servers.py
    -> DynSGDParameterServer.handle_commit).
    """
    meta = dict(meta)
    version = meta.get("version", 0)
    staleness = max(0, version - int(tag))
    scale = 1.0 / (staleness + 1.0)
    new_center = jax.tree.map(
        lambda c, d: c + scale * np.asarray(d), center, delta
    )
    meta["version"] = version + 1
    meta["num_updates"] = meta.get("num_updates", 0) + 1
    return new_center, meta


# -------------------------------------------------------------------- servers


class ParameterServer:
    """Base PS: owns the center pytree + update counter under one lock."""

    commit_rule = staticmethod(delta_rule)

    def __init__(self, params, pull_compress=None):
        from distkeras_tpu.utils.compression import validate_pull_compress

        validate_pull_compress(pull_compress)
        self.pull_compress = pull_compress
        self._center = _to_host(params)
        self._meta = {"num_updates": 0}
        self._lock = threading.Lock()
        self.stopped = threading.Event()
        # snapshot hook: every `snapshot_every` commits, `on_snapshot(n,
        # center_copy, meta_copy)` fires with a copy taken INSIDE the commit's
        # locked section — the state labelled n really is the n-update state
        # even while other workers keep committing (checkpointing uses this)
        self.snapshot_every = 0
        self.on_snapshot = None
        # the multi-consumer face of the same cadence: (every, fn)
        # pairs registered by add_snapshot_listener — checkpointing
        # keeps the single legacy slot above, a serving-bundle
        # publisher rides a listener, each at its own cadence
        self._snapshot_listeners = []
        # fault tolerance (absent upstream — SURVEY §5.3: Spark task retry
        # silently re-trains a partition and the PS double-absorbs its
        # commits): per-worker last-seen commit sequence numbers make commits
        # exactly-once under retry, and last-activity timestamps give the
        # trainer a heartbeat to detect dead workers.
        self._seen_seq = {}  # worker_id -> highest committed seq
        self._activity = {}  # worker_id -> last pull/commit wall time
        # worker-local checkpoint custody: committers hand their host-copied
        # local state to commit(local_snap=...), stored here IN-LOCK. A
        # checkpoint therefore never holds a worker snapshot that is AHEAD
        # of the center it is saved with (the snap lands in the same locked
        # section as its own commit) — behind is fine (the replayed windows
        # dedup), ahead would silently lose commits on resume.
        self._worker_snaps = {}  # worker_id -> host-copy state dict
        # warm-standby replication: sinks registered by attach_replica.
        # Applied (post-dedup) commits forward to every sink INSIDE the
        # commit lock — apply order IS replication order — and each sink
        # awaits the standby's ack before returning, so by the time the
        # committing worker gets ITS ack the standby has applied too
        # (semi-synchronous: worker-acked implies standby-applied; a
        # non-acked commit is resent and deduped on whichever PS serves).
        # A failing sink is detached and closed; its standby re-syncs
        # with a fresh snapshot attach rather than trusting a gapped log.
        self._replicas = []
        self.replication_drops = 0
        # durability gate (require_replicas): when > 0, client commits are
        # REFUSED (typed, retriable "no_replica") while fewer than this
        # many sinks are live — including the resend of a commit that was
        # applied right as its sink died. Closes the semi-sync hole where
        # a commit acked during a replication outage dies with the
        # primary: nothing is acked unless a live sink either received it
        # or attached later with a snapshot that contains it. The goal is
        # kept separately so promotion can relax the gate (sole survivor:
        # availability over durability) and a rejoining standby's attach
        # re-arms it.
        self.min_replicas = 0
        self._min_replicas_goal = 0
        # typed metrics (obs.metrics): client-facing traffic counters
        # plus scrape-time gauges over the meta/replication ledgers the
        # PS already keeps — the training tier's side of the registry
        # the serving tier exposes over its ``metrics`` verb. Per-PS
        # registry: multi-PS processes (tests, standby pairs) keep
        # separate books. ``metrics_snapshot()`` is the read face.
        from distkeras_tpu.obs import (
            FlightRecorder,
            MetricsHistory,
            MetricsRegistry,
        )

        self.registry = MetricsRegistry()
        # the training tier's performance time-series ring (the
        # serving engine's sibling): snapped cadence-guarded from the
        # traffic path (pull/commit — one float compare between
        # snapshots, no new thread), served over the socket tier's
        # b"t" action — windowed commit/pull rates and straggler
        # trends for dkt_top and the autoscaling control loop
        self.history = MetricsHistory(
            self.registry.snapshot, interval=1.0, capacity=600,
        )
        self._metrics = self.registry.group(
            "training_ps",
            ("pulls", "commits", "commits_refused_no_replica"),
        )
        # the training tier's black box: commit-stream positions,
        # replication attach/detach, gate refusals — always-on, dumped
        # by the socket tier's promotion/stand-down post-mortems
        self.recorder = FlightRecorder(capacity=1024)
        self.recorder.register_gauges(self.registry, "training")
        # per-worker commit cadence: one aggregate histogram (register
        # it FIRST, so name-indexed consumers — the SLO evaluator —
        # see the fleet-wide one) plus a labeled histogram per worker,
        # and the straggler gauge = max/median of per-worker mean
        # intervals (the DOWNPOUR/AEASGD lag detector)
        self._interval_hist = self.registry.histogram(
            "training_ps_commit_interval_seconds", start=1e-3,
        )
        self._interval_hists = {}  # wid -> labeled Histogram
        self._commit_last = {}  # wid -> last commit monotonic instant
        self._commit_stats = {}  # wid -> [count, interval_sum]

        def _straggler():
            means = [
                s[1] / s[0]
                for s in list(self._commit_stats.values())
                if s[0] > 0
            ]
            if len(means) < 2:
                return None  # one worker has no one to straggle behind
            means.sort()
            median = means[len(means) // 2]
            return means[-1] / max(median, 1e-9)

        self.registry.gauge("training_ps_straggler", fn=_straggler)
        self.registry.gauge(
            "training_ps_updates",
            fn=lambda: self._meta.get("num_updates", 0),
        )
        self.registry.gauge(
            "training_ps_duplicates",
            fn=lambda: self._meta.get("num_duplicates", 0),
        )
        self.registry.gauge(
            "training_ps_version",
            fn=lambda: self._meta.get("version", 0),
        )
        self.registry.gauge(
            "training_ps_replicas", fn=lambda: len(self._replicas)
        )
        self.registry.gauge(
            "training_ps_min_replicas", fn=lambda: self.min_replicas
        )
        self.registry.gauge(
            "training_ps_replication_drops",
            fn=lambda: self.replication_drops,
        )
        self.registry.gauge(
            "training_ps_workers_seen", fn=lambda: len(self._seen_seq)
        )

    # -- protocol verbs -----------------------------------------------------

    def pull(self, worker_id=None, _via="client"):
        """Return (copy of center, tag). Tag is None unless versioned.

        With ``pull_compress="bfloat16"`` (set by the trainer) the center
        goes out bf16-encoded — half the pull bytes on the DCN path;
        workers decode via ``utils.compression.maybe_decode_pull``. The
        encode happens here, transport-independently, so simulated and
        socket runs see identical pulled values."""
        if _via == "client":
            # explicit chaos hook: fires for worker-facing pulls on BOTH
            # transports (in-process and socket), never for replication
            faults.fire("ps.pull", worker_id=worker_id)
            self.history.maybe_snap()  # traffic IS the cadence
        with self._lock:
            if _via == "client":
                # counter increments ride the commit lock (the
                # registry's counters leave serialization to callers)
                self._metrics.inc("pulls")
            center = jax.tree.map(np.copy, self._center)
            tag = self._pull_tag()
            if worker_id is not None:
                self._activity[worker_id] = time.monotonic()
        if self.pull_compress == "bfloat16":
            from distkeras_tpu.utils.compression import bf16_encode_tree

            center = bf16_encode_tree(center)
        elif self.pull_compress == "int8":
            from distkeras_tpu.utils.compression import int8_encode_tree

            center = int8_encode_tree(center)
        return center, tag

    def commit(self, delta, tag=None, commit_id=None, local_snap=None,
               _via="client"):
        """Apply a delta. ``commit_id=(worker_id, seq)`` makes the commit
        exactly-once: a retried worker re-sends seq numbers the PS has
        already absorbed and they are dropped (counted in meta
        ``num_duplicates``) instead of double-applied.

        ``local_snap``: the committer's host-copied local state (see
        ``AsyncWorker.finish_window``), stored in the same locked section
        as the commit so checkpoints capture worker states consistent with
        (never ahead of) the center. Stored even for a deduped replay —
        the replayed state is at-or-behind the center, which is safe.

        Int8-compressed deltas (``utils.compression``, the workers'
        ``compress="int8"`` wire format) are reconstructed here, before
        the rule — every PS rule and transport sees plain float trees.
        Replication forwards the DECOMPRESSED tree, so the standby applies
        bit-identical values regardless of the worker's wire format.

        ``_via``: "client" for worker-facing commits (the ``ps.commit``
        chaos seam fires); "replicate" for a standby applying its
        primary's forwarded stream (no seam — an injected fault there
        would silently desync the replica instead of exercising a real
        recovery path)."""
        from distkeras_tpu.utils.compression import maybe_decompress

        if _via == "client":
            # explicit chaos hook, BEFORE any state change: an injected
            # raise rejects the commit wholesale and the worker's
            # commit_id resend is the (exactly-once) recovery path
            faults.fire("ps.commit", commit_id=commit_id, tag=tag)
            self.history.maybe_snap()  # traffic IS the cadence
        delta = maybe_decompress(delta)
        snap = None
        with self._lock:
            if _via == "client":
                self._metrics.inc("commits")
            if (
                _via == "client"
                and self.min_replicas
                and len(self._replicas) < self.min_replicas
            ):
                # durability gate: nothing — new commit OR dedup resend —
                # is acked while replication is below requirement; the
                # caller's policy-paced retry rides out the standby's
                # re-attach (which re-arms the gate and, via its fresh
                # snapshot, covers everything applied meanwhile)
                self._metrics.inc("commits_refused_no_replica")
                self.recorder.record(
                    "ps.gate_refused",
                    replicas=len(self._replicas),
                    required=self.min_replicas,
                )
                raise ParameterServerError(
                    "no_replica",
                    detail=f"{len(self._replicas)} of "
                           f"{self.min_replicas} required replicas attached",
                )
            if commit_id is not None:
                wid, seq = commit_id
                now_m = time.monotonic()
                self._activity[wid] = now_m
                if _via == "client":
                    # per-worker commit cadence (straggler detection):
                    # interval since this worker's LAST commit, observed
                    # fleet-wide and per-worker (deduped replays count —
                    # a resend is still worker activity)
                    last = self._commit_last.get(wid)
                    if last is not None:
                        dt = now_m - last
                        self._interval_hist.observe(dt)
                        h = self._interval_hists.get(wid)
                        if h is None:
                            h = self.registry.histogram(
                                "training_ps_commit_interval_seconds",
                                labels={"worker": str(wid)}, start=1e-3,
                            )
                            self._interval_hists[wid] = h
                        h.observe(dt)
                        st = self._commit_stats.setdefault(wid, [0, 0.0])
                        st[0] += 1
                        st[1] += dt
                    self._commit_last[wid] = now_m
                if local_snap is not None:
                    self._worker_snaps[wid] = local_snap
                if seq <= self._seen_seq.get(wid, -1):
                    # deduped replay: NOT forwarded — the standby saw the
                    # original via the stream, so its state (and its own
                    # dedup table) already covers this seq
                    self._meta["num_duplicates"] = (
                        self._meta.get("num_duplicates", 0) + 1
                    )
                    return
                self._seen_seq[wid] = seq
            self._center, self._meta = type(self).commit_rule(
                self._center, self._meta, delta, tag
            )
            # the commit-stream position: a promoted standby's bundle
            # shows exactly how far its stream reached before failover
            self.recorder.record(
                "ps.commit",
                position=self._meta.get("num_updates", 0),
                commit_id=(
                    None if commit_id is None else list(commit_id)
                ),
                via=_via,
            )
            if self._replicas:
                self._forward_to_replicas(delta, tag, commit_id, local_snap)
            # the sink died DURING this commit's forward: applied locally
            # but not durably — refuse the ack (flagged here, raised only
            # AFTER the snapshot bookkeeping below: the commit IS applied
            # and its num_updates step must not lose its checkpoint
            # cadence slot, because the deduped resend early-returns and
            # would never revisit it). The resend is gated until a sink
            # re-attaches, whose snapshot contains this commit, and is
            # then deduped and acked: exactly-once with no unreplicated
            # ack ever issued.
            repl_lost = (
                _via == "client"
                and self.min_replicas
                and len(self._replicas) < self.min_replicas
            )
            n = self._meta.get("num_updates", 0)
            due = [
                fn for every, fn in self._snapshot_cadences()
                if n % every == 0
            ]
            if due:
                snap = (
                    jax.tree.map(np.copy, self._center),
                    self._meta_copy(),
                    dict(self._worker_snaps),
                )
        if snap is not None:
            # heavy IO outside the lock; content still == step n. A snapshot
            # failure (disk full, perms) must not surface as a *worker*
            # failure — the committing thread is an arbitrary worker and
            # retrying it would re-train a healthy partition. One copy
            # feeds every due consumer; each fails independently.
            for fn in due:
                try:
                    fn(n, *snap)
                except Exception:
                    logger.exception(
                        "parameter-server snapshot at step %d failed", n
                    )
        if repl_lost:
            # refusing the ack is safe even though a checkpoint may carry
            # this commit: the checkpoint meta carries the dedup table
            # too, so a post-restore resend of this seq is deduplicated
            with self._lock:
                self._metrics.inc("commits_refused_no_replica")
            raise ParameterServerError(
                "no_replica",
                detail="replication lost mid-commit; the resend is "
                       "deduplicated once a replica re-attaches",
            )

    # -- checkpoint-cadence listeners ---------------------------------------

    def _snapshot_cadences(self):
        """Every (every, fn) checkpoint-cadence consumer: the legacy
        single ``on_snapshot`` slot plus the listener list. Called
        under the commit lock."""
        out = []
        if self.on_snapshot is not None and self.snapshot_every > 0:
            out.append((self.snapshot_every, self.on_snapshot))
        out.extend(self._snapshot_listeners)
        return out

    def add_snapshot_listener(self, fn, every=1):
        """Register ``fn(n, center_copy, meta_copy, worker_snaps)`` to
        fire every ``every`` commits — the multi-consumer face of the
        ``on_snapshot`` hook (checkpointing keeps the legacy slot; a
        serving-bundle publisher rides a listener, each cadence
        independent). Copies are taken INSIDE the commit's locked
        section, so the state labelled n really is the n-update
        state; ``fn`` runs outside the lock and its failure is
        logged, never surfaced to the committing worker. Deduped
        replays do not fire listeners (they never re-apply)."""
        if int(every) < 1:
            raise ValueError(f"every must be >= 1; got {every}")
        with self._lock:
            self._snapshot_listeners.append((int(every), fn))

    def remove_snapshot_listener(self, fn) -> bool:
        """Detach a listener previously registered by
        :meth:`add_snapshot_listener`; True if it was present."""
        with self._lock:
            for i, (_, f) in enumerate(self._snapshot_listeners):
                if f is fn:
                    del self._snapshot_listeners[i]
                    return True
        return False

    # -- replication --------------------------------------------------------

    def attach_replica(self, sink, announce=None):
        """Register a replication sink atomically with a consistent
        snapshot of everything failover must preserve: the center, the
        rule meta (DynSGD version counter included), the exactly-once
        dedup table, and the worker-state custody table.

        ``announce(center, meta, worker_snaps)`` — when given — runs
        INSIDE the commit lock, before the sink is registered: the
        standby's snapshot send and the sink's first forwarded commit
        cannot interleave on the wire, so the stream the standby sees is
        exactly snapshot-then-every-later-commit with no gap and no
        overlap. If ``announce`` raises, the sink is never registered.
        Returns the snapshot triple."""
        with self._lock:
            snap = (
                jax.tree.map(np.copy, self._center),
                self._meta_copy(),
                dict(self._worker_snaps),
            )
            if announce is not None:
                announce(*snap)
            self._replicas.append(sink)
            self.recorder.record(
                "ps.attach",
                replicas=len(self._replicas),
                position=self._meta.get("num_updates", 0),
            )
            # an attach restores durability: re-arm the configured gate
            # (no-op unless require_replicas was ever called)
            self.min_replicas = self._min_replicas_goal
        return snap

    def detach_replica(self, sink) -> None:
        with self._lock:
            if sink in self._replicas:
                self._replicas.remove(sink)

    def require_replicas(self, n: int) -> None:
        """Arm the durability gate: client commits are refused (typed,
        retriable ``no_replica``) while fewer than ``n`` sinks are live.
        Re-armed automatically by every subsequent attach; relaxed by
        ``relax_replication_requirement`` (promotion's sole-survivor
        mode)."""
        with self._lock:
            self.min_replicas = int(n)
            self._min_replicas_goal = int(n)

    def relax_replication_requirement(self) -> None:
        """Drop the ACTIVE durability gate (availability over durability —
        the promoted sole survivor must serve), keeping the goal so a
        rejoining standby's attach re-arms it."""
        with self._lock:
            self.min_replicas = 0

    @property
    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def _forward_to_replicas(self, delta, tag, commit_id, local_snap):
        """Stream one applied commit to every attached sink. Caller holds
        the lock — apply order is replication order, and the committer's
        ack (sent after this returns) implies every live standby applied.
        A sink that fails is detached and closed: the primary keeps
        serving (degraded, counted in ``replication_drops``) and the
        orphaned standby re-syncs with a fresh snapshot attach instead of
        trusting a log with a hole in it."""
        payload = _pack_commit(delta, tag, commit_id, local_snap)
        dead = []
        for sink in self._replicas:
            try:
                sink.replicate(payload)
            except Exception:
                logger.exception(
                    "replication to standby failed; detaching sink"
                )
                dead.append(sink)
        for sink in dead:
            self._replicas.remove(sink)
            self.replication_drops += 1
            self.recorder.record(
                "ps.detach",
                replicas=len(self._replicas),
                position=self._meta.get("num_updates", 0),
            )
            try:
                sink.close()
            except Exception:
                pass

    # -- failure detection --------------------------------------------------

    def suspected_failures(self, timeout: float, now=None):
        """Worker ids whose last pull/commit is older than ``timeout``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return sorted(
                wid
                for wid, last in self._activity.items()
                if now - last > timeout
            )

    @property
    def num_duplicates(self) -> int:
        with self._lock:
            return self._meta.get("num_duplicates", 0)

    def _pull_tag(self):
        return None

    # -- lifecycle / results ------------------------------------------------

    def start(self):
        self.stopped.clear()

    def stop(self):
        self.stopped.set()

    def get_params(self):
        with self._lock:
            return jax.tree.map(np.copy, self._center)

    def reset(self, params):
        with self._lock:
            self._center = _to_host(params)

    def _meta_copy(self):
        """Checkpoint-bound meta: the commit-rule meta plus the exactly-once
        dedup table (worker_id -> highest absorbed seq). Persisting the
        table means a worker that restarts from scratch AFTER a resume
        still cannot double-apply pre-checkpoint commits. Keys go to str
        (the table rides in meta.json); restore normalizes them back.
        Caller must hold the lock."""
        meta = dict(self._meta)
        meta["seen_seq"] = {str(k): int(v) for k, v in self._seen_seq.items()}
        return meta

    def snapshot(self):
        """Consistent (center copy, meta copy) — the checkpoint payload.
        Meta includes the DynSGD version counter and the commit dedup
        table, so staleness and exactly-once bookkeeping survive a
        restore."""
        with self._lock:
            return jax.tree.map(np.copy, self._center), self._meta_copy()

    def restore_snapshot(self, center, meta):
        meta = dict(meta)
        seen = meta.pop("seen_seq", {})
        with self._lock:
            self._center = _to_host(center)
            self._meta = meta
            self._seen_seq = {_wid_key(k): int(v) for k, v in seen.items()}

    def worker_snapshots(self):
        """In-lock copy of the committers' local-state snapshots (the
        end-of-run checkpoint payload)."""
        with self._lock:
            return dict(self._worker_snaps)

    def restore_worker_snapshots(self, snaps: dict):
        """Seed the custody table from a restored checkpoint, so snapshots
        taken BEFORE every worker's first post-resume commit still carry
        the restored worker states instead of silently dropping them."""
        with self._lock:
            self._worker_snaps = {_wid_key(k): v for k, v in snaps.items()}

    def metrics_snapshot(self) -> list:
        """JSON-able samples of the PS registry (counters + ledger
        gauges) — the training tier's analogue of the serving
        ``metrics`` verb payload."""
        return self.registry.snapshot()

    @property
    def num_updates(self) -> int:
        with self._lock:
            return self._meta.get("num_updates", 0)


class DeltaParameterServer(ParameterServer):
    """center += delta — serves DOWNPOUR / AEASGD / EAMSGD."""

    commit_rule = staticmethod(delta_rule)


class ADAGParameterServer(ParameterServer):
    """Applies accumulated-gradient-normalized deltas.

    The normalization (divide the accumulated gradient by the window length)
    happens worker-side (reference: Hermans' AGN; distkeras/workers.py ->
    ADAGWorker), so the server-side rule is the plain delta add; the class
    exists for parity and for server-side instrumentation.
    """

    commit_rule = staticmethod(delta_rule)


class DynSGDParameterServer(ParameterServer):
    """Versioned PS: pull returns the update counter; commits are scaled by
    1/(staleness+1)."""

    commit_rule = staticmethod(dynsgd_rule)

    def __init__(self, params, pull_compress=None):
        super().__init__(params, pull_compress=pull_compress)
        self._meta["version"] = 0

    def _pull_tag(self):
        return self._meta.get("version", 0)


# ------------------------------------------------------- socket (DCN) serving


class _ReplicaSink:
    """Primary-side handle to one attached warm standby. ``replicate``
    runs inside the PS commit lock (see ``_forward_to_replicas``): it
    sends the commit payload and BLOCKS on the standby's 1-byte ack —
    semi-synchronous replication, the property the failover exactly-once
    argument rests on (worker-acked implies standby-applied).

    The socket carries an ack timeout: a standby that stalls without
    closing its socket (stopped process, wedged apply) must become a
    detached sink after a bounded wait, not a primary whose commit lock
    — and with it every worker's pull/commit — is held hostage forever
    (the training tier has no serving-style watchdog to break that)."""

    ACK_TIMEOUT = 10.0

    def __init__(self, conn: socket.socket, on_close=None):
        conn.settimeout(self.ACK_TIMEOUT)
        self.conn = conn
        self._on_close = on_close

    def replicate(self, payload: bytes) -> None:
        faults.fire("ps.replicate", nbytes=len(payload))
        networking.send_data(self.conn, payload)
        ack = self.conn.recv(1)  # socket.timeout is an OSError: sink fails
        if ack != b"k":
            raise ConnectionError("standby did not acknowledge replication")

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass
        if self._on_close is not None:
            try:
                self._on_close()
            except Exception:
                pass


class SocketParameterServer:
    """Serves a ParameterServer over TCP for cross-host workers — as the
    primary, or as a warm standby that follows a primary and promotes on
    its loss.

    Protocol (reference: distkeras/parameter_servers.py ->
    SocketParameterServer.run, extended): connection sends a 1-byte
    action; every reply leads with a status byte — b"k" (ok) or b"e"
    followed by a typed error frame ``{"error": code, ...}``:

    - b"p": pull -> request frame {"worker_id"} -> b"k" + frame {"tag"}
      + center;
    - b"c": commit -> frame {"tag", "commit_id", "wrapped"} + delta
      (+snap), reply b"k";
    - b"a": replica attach -> request frame (reserved) -> b"k" + snapshot
      frame {"meta"} + {center, workers}; the connection then becomes the
      replication channel — the primary streams every applied commit and
      the standby acks each with b"k";
    - b"m": metrics scrape -> b"k" + frame {"metrics", "role", "port"}
      (the typed-registry snapshot; served in BOTH roles so a standby
      is observable before it promotes — ``dkt_top --ps`` polls this);
    - b"t": timeseries digest; the action byte is followed by a knob
      frame ({"window", "names", "points"}, {} = defaults) ->
      b"k" + frame {"timeseries", "role", "port"} (the history ring's
      windowed rates/trends — the training-tier face of the serving
      ``timeseries`` verb; ``dkt_top --ps --window`` rides the knob);
    - b"s": stop the server;
    - anything else: b"e" + ``unknown_action`` frame and the connection
      closes — the old server silently ignored unknown bytes and re-read
      mid-frame payload bytes as actions, a protocol desync that turned
      one bad byte into an unbounded garbage conversation.

    All frames are the pickle-free JSON-header + npz format from
    ``utils.serialization`` — the reference pickled these payloads, which is
    arbitrary-code-execution on whichever host unpickles them.
    One thread per connection; commits serialize on the PS lock.

    **Standby role** (``standby_of=(host, port)``): on ``start()`` the
    server dials the primary, attaches (consistent snapshot restore —
    center, meta incl. the DynSGD version counter, dedup table, worker
    snapshots), then follows the replication stream on a background
    thread. While in standby role, client verbs are refused with a typed
    ``standby`` error. If the stream dies but the primary still answers,
    the standby RE-ATTACHES (fresh snapshot — never trusts a gapped log);
    if the primary is unreachable, it PROMOTES: role flips to "primary",
    verbs start serving, and ``on_promote(self)`` fires. Promotion is
    safe exactly-once territory because the dedup table rode the stream:
    a worker's resend of an in-doubt commit is applied iff the standby
    never saw it, deduped iff it did.
    """

    def __init__(self, ps: ParameterServer, host="0.0.0.0", port=0,
                 standby_of=None, auto_promote=True, attach_retry=None,
                 on_promote=None, postmortem_dir=None):
        """``postmortem_dir``: where PROMOTION and STAND-DOWN — the
        training tier's terminal events — dump a post-mortem bundle
        (the wrapped PS's flight-recorder ring: commit-stream
        positions, replication attach/detach, armed seam firings —
        plus its metrics snapshot). None keeps the latest bundle in
        memory only (``last_postmortem``)."""
        self.ps = ps
        self.postmortem_dir = postmortem_dir
        self.last_postmortem = None
        self.last_postmortem_path = None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        self.standby_of = tuple(standby_of) if standby_of is not None else None
        self.role = "primary" if standby_of is None else "standby"
        self.promoted = False
        self.promote_reason = None
        self.auto_promote = bool(auto_promote)
        self.on_promote = on_promote
        self.reattaches = 0
        self.killed = False
        # re-attach pacing: a few quick policy-paced tries distinguish "the
        # stream hiccuped" (primary alive: re-sync) from "the primary is
        # gone" (every dial refused: promote). Short on purpose — workers
        # are backing off against a dead endpoint while this runs.
        self._attach_retry = attach_retry or networking.RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.2, budget=2.0
        )
        self._accept_thread = None
        self._repl_thread = None
        self._repl_conn = None  # standby side's stream (closed on stop/kill)
        self._conn_threads = []
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._role_lock = threading.Lock()
        self._running = threading.Event()
        # socket-tier gauges ride the wrapped PS's registry, so one
        # metrics_snapshot() covers commits AND failover posture
        self.ps.registry.gauge(
            "training_ps_socket_reattaches", fn=lambda: self.reattaches
        )
        self.ps.registry.gauge(
            "training_ps_socket_promoted", fn=lambda: self.promoted
        )
        self.ps.registry.gauge(
            "training_ps_socket_open_connections",
            fn=lambda: len(self._conns),
        )

    def start(self):
        self.ps.start()
        self._running.set()
        # armed ps.*/net.* seam firings land in the PS ring, so a
        # promotion bundle names the chaos that preceded the failover
        faults.add_observer(self.ps.recorder.fault_observer)
        if self.role == "standby":
            # synchronous first sync: when start() returns, the standby is
            # commit-identical to the primary and following its stream
            conn = self._attach_to_primary()
            self._repl_thread = threading.Thread(
                target=self._follow, args=(conn,), daemon=True
            )
            self._repl_thread.start()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    # -- standby side -------------------------------------------------------

    def _attach_to_primary(self) -> socket.socket:
        """Dial the primary, attach, restore its consistent snapshot into
        the local PS; returns the (now replication) connection."""
        host, port = self.standby_of
        # short dial timeout: a primary that dies WITHOUT an RST (power
        # loss, partition) must not stall each probe 30s — the promotion
        # decision is budgeted in seconds, and this timeout is what keeps
        # the dial inside that budget
        conn = networking.connect(host, port, timeout=2.0)
        try:
            conn.sendall(b"a")
            networking.send_data(conn, pack_frame({"replica_port": self.port}))
            _read_reply_status(conn)
            header, blob = unpack_frame(networking.recv_data(conn))
            tree = deserialize_params(blob)
            self.ps.restore_snapshot(tree["center"], header.get("meta", {}))
            self.ps.restore_worker_snapshots(tree.get("workers", {}))
            self.ps.recorder.record(
                "ps.sync",
                primary=f"{host}:{port}",
                position=self.ps.num_updates,
            )
        except BaseException:
            try:
                conn.close()
            except OSError:
                pass
            raise
        self._repl_conn = conn
        return conn

    def _follow(self, conn: socket.socket):
        """Replication pump: apply each forwarded commit, ack it, repeat.
        Stream death -> re-attach (primary alive) or promote (primary
        gone). Any apply/decode failure (e.g. a corrupted payload under
        wire chaos) also re-syncs from a fresh snapshot — a replica must
        never keep following a stream it may have misapplied."""
        while self._running.is_set() and self.role == "standby":
            try:
                data = networking.recv_data(conn)
                _apply_commit_payload(self.ps, data, _via="replicate")
                conn.sendall(b"k")
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass
                if not (self._running.is_set() and self.role == "standby"):
                    return
                conn = self._reattach_or_promote()
                if conn is None:
                    return
        try:
            conn.close()
        except OSError:
            pass

    def _reattach_or_promote(self):
        """The standby's liveness judgment: if the primary still answers,
        re-sync (fresh snapshot) and keep following; if it is gone,
        promote (when ``auto_promote``). Returns the new replication
        connection, or None when this follower thread should exit.

        Only CONNECTION-level failure justifies promotion: a snapshot
        that arrives but fails to decode (wire corruption under chaos)
        proves the primary is alive, and promoting on it would
        split-brain — a frozen 'promoted' standby that the trainer's
        ``active_parameter_server`` would prefer over the live primary,
        silently losing every later commit. Decode/apply failures retry
        the attach; if they persist, the standby stands down (stops
        following, does NOT promote) and logs loudly."""
        for _ in range(3):
            try:
                conn = self._attach_retry.call(self._attach_to_primary)
                self.reattaches += 1
                self.ps.recorder.record(
                    "ps.reattach", count=self.reattaches,
                    position=self.ps.num_updates,
                )
                logger.warning(
                    "standby on port %d re-attached to primary %s "
                    "(re-sync #%d)",
                    self.port, self.standby_of, self.reattaches,
                )
                return conn
            except (ConnectionError, OSError):
                break  # primary unreachable: promotion territory
            except Exception:
                logger.exception(
                    "standby re-attach failed on a non-connection error; "
                    "retrying"
                )
        else:
            logger.error(
                "standby on port %d cannot decode the primary's snapshot "
                "but the primary still answers — standing down (not "
                "promoting; a split brain would lose commits)",
                self.port,
            )
            self.ps.recorder.record(
                "ps.stand_down", position=self.ps.num_updates,
            )
            self.dump_postmortem(
                "stand_down",
                detail={"primary": list(self.standby_of)},
            )
            return None
        if self._running.is_set() and self.auto_promote:
            self.promote(reason="primary-lost")
        return None

    def promote(self, reason="manual"):
        """Standby -> primary: flip the role, start serving client verbs.
        Idempotent; fires ``on_promote(self)`` exactly once. The PS state
        needs no fixup — replication kept the center, version counters,
        dedup table, and worker snapshots commit-identical."""
        with self._role_lock:
            if self.role == "primary":
                return
            self.role = "primary"
            self.promoted = True
            self.promote_reason = reason
        # sole-survivor mode: the new primary has no standby of its own
        # yet, and a durability gate inherited from the dead primary's
        # topology would refuse every commit forever. Serve degraded; a
        # rejoining standby's attach re-arms the gate.
        self.ps.relax_replication_requirement()
        self.ps.recorder.record(
            "ps.promoted", reason=reason,
            position=self.ps.num_updates,
            reattaches=self.reattaches,
        )
        # promotion IS the training tier's terminal event: the old
        # primary is dead and this ring holds the last evidence of how
        # far its stream reached — dump before serving a single commit
        self.dump_postmortem("promotion", detail={"reason": reason})
        logger.warning(
            "parameter-server standby on port %d promoted to primary (%s)",
            self.port, reason,
        )
        cb = self.on_promote
        if cb is not None:
            try:
                cb(self)
            except Exception:
                logger.exception("on_promote callback failed")

    def dump_postmortem(self, reason: str, detail=None):
        """The training tier's post-mortem bundle (shared
        ``obs.dump_postmortem`` schema): the wrapped PS's recorder ring
        (commit-stream positions, replication attach/detach, gate
        refusals, armed seam firings), its metrics snapshot, the
        worker-activity table as the in-flight view, and the failover
        config. Returns ``(bundle, path)``."""
        from distkeras_tpu.obs import dump_postmortem as _dump

        with self.ps._lock:
            in_flight = [
                {
                    "worker_id": wid,
                    "last_seq": self.ps._seen_seq.get(wid),
                    "idle_seconds": round(
                        time.monotonic() - last, 3
                    ),
                }
                for wid, last in self.ps._activity.items()
            ]
        bundle, path = _dump(
            self.postmortem_dir, "parameter_server", reason,
            recorder=self.ps.recorder,
            metrics=self.ps.metrics_snapshot(),
            in_flight=in_flight,
            config={
                "role": self.role,
                "standby_of": (
                    None if self.standby_of is None
                    else list(self.standby_of)
                ),
                "port": self.port,
                "min_replicas": self.ps.min_replicas,
                "rule": type(self.ps).__name__,
            },
            detail=detail,
        )
        self.last_postmortem = bundle
        self.last_postmortem_path = path
        return bundle, path

    # -- serving side -------------------------------------------------------

    def _accept_loop(self):
        self._listener.settimeout(0.2)
        while self._running.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            # reap as we go: finished connection threads used to pile up
            # for the server's lifetime (one entry per client connect —
            # unbounded growth under connection churn)
            self._conn_threads = [
                th for th in self._conn_threads if th.is_alive()
            ]
            self._conn_threads.append(t)

    def _serve(self, conn: socket.socket):
        with self._conns_lock:
            self._conns.add(conn)
        handed_off = False
        try:
            while self._running.is_set():
                action = conn.recv(1)
                if not action:
                    break
                if action == b"p":
                    # pull request: JSON header {"worker_id": ...} (None for
                    # anonymous) — keeps the heartbeat live for remote
                    # workers too. No pickle anywhere on this path.
                    data = networking.recv_data(conn)
                    if self.role != "primary":
                        _send_error(conn, "standby")
                        continue
                    header, _ = unpack_frame(data)
                    try:
                        center, tag = self.ps.pull(
                            worker_id=header.get("worker_id")
                        )
                    except Exception as e:
                        # a failed verb must not kill the stream: the full
                        # request frame was already consumed, so a typed
                        # error reply leaves the protocol in sync and the
                        # client's (idempotent) retry does the recovery
                        _send_error(conn, "internal", detail=repr(e))
                        continue
                    conn.sendall(b"k")
                    networking.send_data(
                        conn, pack_frame({"tag": tag}, serialize_params(center))
                    )
                elif action == b"c":
                    data = networking.recv_data(conn)
                    if self.role != "primary":
                        _send_error(conn, "standby")
                        continue
                    try:
                        _apply_commit_payload(self.ps, data)
                    except ParameterServerError as e:
                        # already typed (the durability gate's
                        # no_replica): forward the code as-is
                        _send_error(conn, e.code, detail=e.detail)
                        continue
                    except Exception as e:
                        # commit rejected (e.g. an armed ps.commit seam)
                        # BEFORE apply: typed reply; the worker's
                        # commit_id resend is exactly-once under dedup
                        _send_error(conn, "internal", detail=repr(e))
                        continue
                    conn.sendall(b"k")
                elif action == b"a":
                    data = networking.recv_data(conn)
                    if self.role != "primary":
                        # chained standbys are not supported: a replica of
                        # a replica would double the promotion ambiguity
                        _send_error(conn, "standby")
                        continue
                    unpack_frame(data)  # attach header (reserved fields)
                    # on_close keeps _conns bounded: every standby re-sync
                    # is a fresh attach connection, and a detached sink's
                    # socket must leave the tracked set (the same
                    # unbounded-growth class as the _conn_threads fix)
                    sink = _ReplicaSink(
                        conn, on_close=lambda c=conn: self._discard_conn(c)
                    )

                    def announce(center, meta, worker_snaps):
                        # runs INSIDE the PS commit lock (attach_replica):
                        # snapshot-then-stream with no interleaving window
                        conn.sendall(b"k")
                        networking.send_data(
                            conn,
                            pack_frame(
                                {"meta": meta},
                                serialize_params({
                                    "center": center,
                                    "workers": {
                                        str(k): v
                                        for k, v in worker_snaps.items()
                                        if v is not None
                                    },
                                }),
                            ),
                        )

                    self.ps.attach_replica(sink, announce)
                    # the sink owns this socket now: commits pump it from
                    # inside the PS lock; this thread's job is done
                    handed_off = True
                    return
                elif action == b"m":
                    # metrics scrape (works on standby AND primary —
                    # observability must not be gated on role): b"k" +
                    # frame {"metrics": samples, "role": ...}; what
                    # ``dkt_top --ps`` polls
                    conn.sendall(b"k")
                    networking.send_data(
                        conn,
                        pack_frame({
                            "metrics": self.ps.metrics_snapshot(),
                            "role": self.role,
                            "port": self.port,
                        }),
                    )
                elif action == b"t":
                    # timeseries digest (both roles, like b"m"): the
                    # PS history ring's windowed commit/pull rates,
                    # straggler trend, and sparkline points. The
                    # action carries a knob frame (window/names/
                    # points — ``dkt_top --ps --window`` rides it)
                    knobs, _ = unpack_frame(networking.recv_data(conn))
                    self.ps.history.maybe_snap()
                    kw = {}
                    if knobs.get("window") is not None:
                        kw["window"] = float(knobs["window"])
                    if knobs.get("names") is not None:
                        kw["names"] = list(knobs["names"])
                    if knobs.get("points") is not None:
                        kw["points"] = int(knobs["points"])
                    conn.sendall(b"k")
                    networking.send_data(
                        conn,
                        pack_frame({
                            "timeseries": self.ps.history.digest(**kw),
                            "role": self.role,
                            "port": self.port,
                        }),
                    )
                elif action == b"s":
                    self.stop()
                    break
                else:
                    _send_error(
                        conn, "unknown_action", action=action.hex()
                    )
                    break
        except (ConnectionError, OSError):
            pass
        except Exception:
            # a malformed/corrupted REQUEST frame (wire chaos) — drop the
            # connection; the client's retry machinery takes it from here
            logger.debug("parameter-server connection dropped", exc_info=True)
        finally:
            if not handed_off:
                with self._conns_lock:
                    self._conns.discard(conn)
                try:
                    conn.close()
                except OSError:
                    pass

    # -- lifecycle ----------------------------------------------------------

    def _discard_conn(self, conn) -> None:
        with self._conns_lock:
            self._conns.discard(conn)

    def _close_all(self, rst=False):
        try:
            self._listener.close()
        except OSError:
            pass
        repl = self._repl_conn
        if repl is not None:
            try:  # unblock a standby's follower from its recv
                repl.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            if rst:
                try:  # SO_LINGER 0: abort with RST, as a dying process would
                    c.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                except OSError:
                    pass
            try:
                c.close()
            except OSError:
                pass

    def stop(self):
        self._running.clear()
        faults.remove_observer(self.ps.recorder.fault_observer)
        self.ps.stop()
        self._close_all()
        # join what we spawned (skip the current thread: stop() runs on a
        # serve thread for the b"s" verb) — with the accept-loop reap this
        # closes the old unbounded `_conn_threads` growth end to end
        me = threading.current_thread()
        for t in [self._accept_thread, self._repl_thread, *self._conn_threads]:
            if t is not None and t is not me:
                t.join(timeout=2.0)
        self._conn_threads = [t for t in self._conn_threads if t.is_alive()]

    def kill(self):
        """Simulate primary process death for chaos tests: no drain, no
        goodbye — the listener and every open connection (client AND
        replication) drop with an RST mid-whatever-they-were-doing. The
        PS object is left untouched (a dead process doesn't tidy its
        state). Only tests and the chaos soak call this."""
        self.killed = True
        self._running.clear()
        # a dead process's observer cannot fire; in-process simulations
        # must match, or the victim's ring keeps taping after "death"
        faults.remove_observer(self.ps.recorder.fault_observer)
        self._close_all(rst=True)


def _read_reply_status(sock: socket.socket) -> None:
    """Consume a reply's status byte; raise the typed error a b"e" frame
    carries. THE client-side decoder for the status-byte protocol."""
    status = sock.recv(1)
    if status == b"k":
        return
    if status == b"e":
        header, _ = unpack_frame(networking.recv_data(sock))
        code = header.get("error", "error")
        if code == "standby":
            raise StandbyError(header.get("detail"))
        raise ParameterServerError(code, detail=header.get("detail"))
    if not status:
        raise ConnectionError("parameter-server stream closed")
    raise ConnectionError(
        f"parameter-server protocol desync: bad status byte {status!r}"
    )


class RemoteParameterServerClient:
    """Worker-side proxy speaking the socket protocol; drop-in for a local
    PS. With an endpoint list it is failover-aware: the dial is sticky —
    it keeps the endpoint that last worked and rotates onward only when
    that one dies."""

    def __init__(self, host=None, port=None, retry=None, endpoints=None,
                 on_failover=None):
        """``retry``: optional ``networking.RetryPolicy`` — the SAME
        backoff implementation the serving client uses, so the training
        and serving tiers cannot drift apart on retry semantics. It paces
        ``reconnect()`` redials AND the transparent in-operation failover:
        when a pull/commit dies mid-stream, the client redials (rotating
        endpoints) and resends under the policy — pulls always (they are
        idempotent), commits only when a ``commit_id`` is present (the
        dedup table makes the resend exactly-once; an id-less commit
        cannot be safely resent and surfaces its failure instead).

        ``endpoints``: list of ``(host, port)`` alternatives — typically
        ``[primary, standby]``. ``on_failover(endpoint)`` fires whenever
        the dial lands on a different endpoint than before (observability
        only; exceptions are swallowed)."""
        if endpoints is None:
            if host is None or port is None:
                raise ValueError(
                    "RemoteParameterServerClient needs host+port or an "
                    "endpoints list"
                )
            endpoints = [(host, port)]
        self.endpoints = [tuple(e) for e in endpoints]
        self.retry = retry
        self.on_failover = on_failover
        self.failovers = 0
        # per-endpoint dial timeout: during a failover the rotation must
        # reach the standby in seconds even when the dead primary drops
        # SYNs silently (no RST) — connect()'s default 30s per endpoint
        # would eat the whole retry budget before the first rotation
        self.dial_timeout = 5.0
        self._lock = threading.Lock()
        self._sock, self._ep = networking.connect_any(
            self.endpoints, timeout=self.dial_timeout
        )
        self.host, self.port = self.endpoints[self._ep]

    @property
    def endpoint(self):
        """The ``(host, port)`` currently connected."""
        return self.endpoints[self._ep]

    def _dial_locked(self, start_offset=0):
        """One rotation over the endpoint list starting at the sticky
        index (+``start_offset``); updates bookkeeping and fires
        ``on_failover`` on a move. Caller holds the lock."""
        sock, i = networking.connect_any(
            self.endpoints, start=self._ep + start_offset,
            timeout=self.dial_timeout,
        )
        if i != self._ep:
            self._ep = i
            self.host, self.port = self.endpoints[i]
            self.failovers += 1
            cb = self.on_failover
            if cb is not None:
                try:
                    cb(self.endpoints[i])
                except Exception:
                    logger.exception("on_failover callback failed")
        self._sock = sock

    def _reconnect_locked(self, rotate_first=False):
        """``rotate_first``: start the dial at the NEXT endpoint — the
        current one answered but refused (a live standby), so redialing
        it first would livelock against a healthy, dialable primary."""
        try:
            self._sock.close()
        except OSError:
            pass
        self._dial_locked(start_offset=1 if rotate_first else 0)

    def reconnect(self):
        """Fresh connection — a retried worker must not reuse a stream that
        may have died mid-message (half-written commit payloads would
        desync the protocol). Policy-paced when ``retry`` is set; the
        redial rotates through the endpoint list, so a worker retrying
        into a failover lands on the promoted standby."""
        with self._lock:
            if self.retry is not None:
                self.retry.call(self._reconnect_locked)
            else:
                self._reconnect_locked()

    def _with_failover(self, op, resend_safe=True):
        """Run ``op`` once; on a dead/refusing stream, redial (rotating
        endpoints) and resend under ``self.retry``. ``StandbyError`` is a
        ``ConnectionError``, so the not-yet-promoted window during a
        failover is absorbed by the same policy-paced loop — and because
        a standby ANSWERS the dial, a standby refusal rotates the next
        redial past it (a sticky redial would otherwise never try a
        healthy primary again)."""
        try:
            return op()
        except (ConnectionError, OSError) as first:
            if self.retry is None or not resend_safe:
                raise
            last = [first]

            def redo():
                e = last[0]
                rotate = isinstance(e, StandbyError)
                # a typed reply on a healthy stream (no_replica, internal)
                # needs no teardown — retry in place; redial only when the
                # stream is dead/suspect, or rotating off a live standby
                if rotate or not getattr(e, "stream_in_sync", False):
                    with self._lock:
                        self._reconnect_locked(rotate_first=rotate)
                try:
                    return op()
                except (ConnectionError, OSError) as err:
                    last[0] = err
                    raise

            return self.retry.call(redo)

    def pull(self, worker_id=None):
        def op():
            with self._lock:
                self._sock.sendall(b"p")
                networking.send_data(
                    self._sock, pack_frame({"worker_id": worker_id})
                )
                _read_reply_status(self._sock)
                header, blob = unpack_frame(networking.recv_data(self._sock))
            return deserialize_params(blob), header.get("tag")

        return self._with_failover(op)

    def commit(self, delta, tag=None, commit_id=None, local_snap=None):
        payload = _pack_commit(_to_host(delta), tag, commit_id, local_snap)

        def op():
            with self._lock:
                self._sock.sendall(b"c")
                networking.send_data(self._sock, payload)
                try:
                    _read_reply_status(self._sock)
                except ParameterServerError:
                    raise  # typed reply: the stream is still in sync
                except ConnectionError as e:
                    # the ack never arrived — the commit is IN DOUBT
                    # (applied-but-unacked or never-received); the typed
                    # error names which one so the resend/escalation
                    # decision is made on facts
                    raise CommitNotAcknowledgedError(
                        commit_id, detail=str(e)
                    ) from e

        return self._with_failover(op, resend_safe=commit_id is not None)

    def metrics(self) -> dict:
        """Scrape the connected PS's typed-metrics snapshot (works on
        a standby too): ``{"metrics": samples, "role", "port"}``."""

        def op():
            with self._lock:
                self._sock.sendall(b"m")
                _read_reply_status(self._sock)
                header, _ = unpack_frame(networking.recv_data(self._sock))
            return header

        return self._with_failover(op)

    def timeseries(self, window=None, names=None, points=None) -> dict:
        """The connected PS's windowed time-series digest
        (``obs.MetricsHistory.digest`` over the training registry;
        works on a standby too): ``{"timeseries": digest, "role",
        "port"}``. ``window``/``names``/``points`` ride a knob frame
        to the ``b"t"`` action (None = the digest defaults)."""
        knobs = {}
        if window is not None:
            knobs["window"] = float(window)
        if names is not None:
            knobs["names"] = list(names)
        if points is not None:
            knobs["points"] = int(points)

        def op():
            with self._lock:
                self._sock.sendall(b"t")
                networking.send_data(self._sock, pack_frame(knobs))
                _read_reply_status(self._sock)
                header, _ = unpack_frame(networking.recv_data(self._sock))
            return header

        return self._with_failover(op)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
