"""Parameter servers for the asynchronous trainers.

Re-creation of the reference's PS runtime (reference:
distkeras/parameter_servers.py -> ParameterServer / SocketParameterServer /
DeltaParameterServer / ADAGParameterServer / DynSGDParameterServer) with the
same pull/commit verbs and per-algorithm commit rules, re-homed for TPU:

- The center variable is a host-resident pytree (numpy leaves — commits are
  in-place host adds, no device round-trip).
- In-process workers (threads driving per-chip windows) call ``pull`` /
  ``commit`` directly under a lock — the single-host fast path.
- ``SocketParameterServer`` serves the same PS object over TCP for
  cross-host (DCN) workers, with the reference's one-byte action protocol:
  b"p" pull, b"c" commit, b"s" stop.

Every commit rule is also exposed as a pure function
(``center', meta' = RULE(center, meta, delta, tag)``) so tests can assert
staleness/normalization semantics exactly (SURVEY §7.4).
"""

from __future__ import annotations

import logging
import socket
import threading
import time

logger = logging.getLogger(__name__)

import jax
import numpy as np

from distkeras_tpu import networking
from distkeras_tpu.utils.serialization import (
    deserialize_params,
    pack_frame,
    serialize_params,
    unpack_frame,
)


def _to_host(tree):
    """Host numpy copies with float leaves normalized to float32.

    Integer/bool leaves keep their dtype: the compressed wire formats
    (int8 ``q`` trees, uint16 bf16 payloads, int32 top-k indices) must
    not be silently re-inflated to 4-byte floats — the old unconditional
    float32 coercion cost the remote-PS path most of its compression
    byte savings and turned top-k index arrays into floats (r4)."""

    def conv(a):
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
            return a
        return a.astype(np.float32, copy=False)

    return jax.tree.map(conv, tree)


# --------------------------------------------------------------------- rules
# Pure commit rules (testable without threads; reference: §4.2/§4.3 semantics)


def _wid_key(k):
    """Worker ids round-trip through JSON meta / str-keyed trees as strings;
    normalize back to int where possible."""
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def delta_rule(center, meta, delta, tag=None):
    """center += delta (DOWNPOUR / AEASGD / EAMSGD / ADAG commits)."""
    new_center = jax.tree.map(lambda c, d: c + np.asarray(d), center, delta)
    meta = dict(meta)
    meta["num_updates"] = meta.get("num_updates", 0) + 1
    return new_center, meta


def dynsgd_rule(center, meta, delta, tag):
    """Staleness-aware: center += delta / (staleness + 1).

    ``tag`` is the update counter the worker saw at pull time; staleness is
    how many commits landed since (reference: distkeras/parameter_servers.py
    -> DynSGDParameterServer.handle_commit).
    """
    meta = dict(meta)
    version = meta.get("version", 0)
    staleness = max(0, version - int(tag))
    scale = 1.0 / (staleness + 1.0)
    new_center = jax.tree.map(
        lambda c, d: c + scale * np.asarray(d), center, delta
    )
    meta["version"] = version + 1
    meta["num_updates"] = meta.get("num_updates", 0) + 1
    return new_center, meta


# -------------------------------------------------------------------- servers


class ParameterServer:
    """Base PS: owns the center pytree + update counter under one lock."""

    commit_rule = staticmethod(delta_rule)

    def __init__(self, params, pull_compress=None):
        from distkeras_tpu.utils.compression import validate_pull_compress

        validate_pull_compress(pull_compress)
        self.pull_compress = pull_compress
        self._center = _to_host(params)
        self._meta = {"num_updates": 0}
        self._lock = threading.Lock()
        self.stopped = threading.Event()
        # snapshot hook: every `snapshot_every` commits, `on_snapshot(n,
        # center_copy, meta_copy)` fires with a copy taken INSIDE the commit's
        # locked section — the state labelled n really is the n-update state
        # even while other workers keep committing (checkpointing uses this)
        self.snapshot_every = 0
        self.on_snapshot = None
        # fault tolerance (absent upstream — SURVEY §5.3: Spark task retry
        # silently re-trains a partition and the PS double-absorbs its
        # commits): per-worker last-seen commit sequence numbers make commits
        # exactly-once under retry, and last-activity timestamps give the
        # trainer a heartbeat to detect dead workers.
        self._seen_seq = {}  # worker_id -> highest committed seq
        self._activity = {}  # worker_id -> last pull/commit wall time
        # worker-local checkpoint custody: committers hand their host-copied
        # local state to commit(local_snap=...), stored here IN-LOCK. A
        # checkpoint therefore never holds a worker snapshot that is AHEAD
        # of the center it is saved with (the snap lands in the same locked
        # section as its own commit) — behind is fine (the replayed windows
        # dedup), ahead would silently lose commits on resume.
        self._worker_snaps = {}  # worker_id -> host-copy state dict

    # -- protocol verbs -----------------------------------------------------

    def pull(self, worker_id=None):
        """Return (copy of center, tag). Tag is None unless versioned.

        With ``pull_compress="bfloat16"`` (set by the trainer) the center
        goes out bf16-encoded — half the pull bytes on the DCN path;
        workers decode via ``utils.compression.maybe_decode_pull``. The
        encode happens here, transport-independently, so simulated and
        socket runs see identical pulled values."""
        with self._lock:
            center = jax.tree.map(np.copy, self._center)
            tag = self._pull_tag()
            if worker_id is not None:
                self._activity[worker_id] = time.monotonic()
        if self.pull_compress == "bfloat16":
            from distkeras_tpu.utils.compression import bf16_encode_tree

            center = bf16_encode_tree(center)
        elif self.pull_compress == "int8":
            from distkeras_tpu.utils.compression import int8_encode_tree

            center = int8_encode_tree(center)
        return center, tag

    def commit(self, delta, tag=None, commit_id=None, local_snap=None):
        """Apply a delta. ``commit_id=(worker_id, seq)`` makes the commit
        exactly-once: a retried worker re-sends seq numbers the PS has
        already absorbed and they are dropped (counted in meta
        ``num_duplicates``) instead of double-applied.

        ``local_snap``: the committer's host-copied local state (see
        ``AsyncWorker.finish_window``), stored in the same locked section
        as the commit so checkpoints capture worker states consistent with
        (never ahead of) the center. Stored even for a deduped replay —
        the replayed state is at-or-behind the center, which is safe.

        Int8-compressed deltas (``utils.compression``, the workers'
        ``compress="int8"`` wire format) are reconstructed here, before
        the rule — every PS rule and transport sees plain float trees."""
        from distkeras_tpu.utils.compression import maybe_decompress

        delta = maybe_decompress(delta)
        snap = None
        with self._lock:
            if commit_id is not None:
                wid, seq = commit_id
                self._activity[wid] = time.monotonic()
                if local_snap is not None:
                    self._worker_snaps[wid] = local_snap
                if seq <= self._seen_seq.get(wid, -1):
                    self._meta["num_duplicates"] = (
                        self._meta.get("num_duplicates", 0) + 1
                    )
                    return
                self._seen_seq[wid] = seq
            self._center, self._meta = type(self).commit_rule(
                self._center, self._meta, delta, tag
            )
            n = self._meta.get("num_updates", 0)
            cb = self.on_snapshot
            if (
                cb is not None
                and self.snapshot_every > 0
                and n % self.snapshot_every == 0
            ):
                snap = (
                    jax.tree.map(np.copy, self._center),
                    self._meta_copy(),
                    dict(self._worker_snaps),
                )
        if snap is not None:
            # heavy IO outside the lock; content still == step n. A snapshot
            # failure (disk full, perms) must not surface as a *worker*
            # failure — the committing thread is an arbitrary worker and
            # retrying it would re-train a healthy partition.
            try:
                cb(n, *snap)
            except Exception:
                logger.exception("parameter-server snapshot at step %d failed", n)

    # -- failure detection --------------------------------------------------

    def suspected_failures(self, timeout: float, now=None):
        """Worker ids whose last pull/commit is older than ``timeout``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return sorted(
                wid
                for wid, last in self._activity.items()
                if now - last > timeout
            )

    @property
    def num_duplicates(self) -> int:
        with self._lock:
            return self._meta.get("num_duplicates", 0)

    def _pull_tag(self):
        return None

    # -- lifecycle / results ------------------------------------------------

    def start(self):
        self.stopped.clear()

    def stop(self):
        self.stopped.set()

    def get_params(self):
        with self._lock:
            return jax.tree.map(np.copy, self._center)

    def reset(self, params):
        with self._lock:
            self._center = _to_host(params)

    def _meta_copy(self):
        """Checkpoint-bound meta: the commit-rule meta plus the exactly-once
        dedup table (worker_id -> highest absorbed seq). Persisting the
        table means a worker that restarts from scratch AFTER a resume
        still cannot double-apply pre-checkpoint commits. Keys go to str
        (the table rides in meta.json); restore normalizes them back.
        Caller must hold the lock."""
        meta = dict(self._meta)
        meta["seen_seq"] = {str(k): int(v) for k, v in self._seen_seq.items()}
        return meta

    def snapshot(self):
        """Consistent (center copy, meta copy) — the checkpoint payload.
        Meta includes the DynSGD version counter and the commit dedup
        table, so staleness and exactly-once bookkeeping survive a
        restore."""
        with self._lock:
            return jax.tree.map(np.copy, self._center), self._meta_copy()

    def restore_snapshot(self, center, meta):
        meta = dict(meta)
        seen = meta.pop("seen_seq", {})
        with self._lock:
            self._center = _to_host(center)
            self._meta = meta
            self._seen_seq = {_wid_key(k): int(v) for k, v in seen.items()}

    def worker_snapshots(self):
        """In-lock copy of the committers' local-state snapshots (the
        end-of-run checkpoint payload)."""
        with self._lock:
            return dict(self._worker_snaps)

    def restore_worker_snapshots(self, snaps: dict):
        """Seed the custody table from a restored checkpoint, so snapshots
        taken BEFORE every worker's first post-resume commit still carry
        the restored worker states instead of silently dropping them."""
        with self._lock:
            self._worker_snaps = {_wid_key(k): v for k, v in snaps.items()}

    @property
    def num_updates(self) -> int:
        with self._lock:
            return self._meta.get("num_updates", 0)


class DeltaParameterServer(ParameterServer):
    """center += delta — serves DOWNPOUR / AEASGD / EAMSGD."""

    commit_rule = staticmethod(delta_rule)


class ADAGParameterServer(ParameterServer):
    """Applies accumulated-gradient-normalized deltas.

    The normalization (divide the accumulated gradient by the window length)
    happens worker-side (reference: Hermans' AGN; distkeras/workers.py ->
    ADAGWorker), so the server-side rule is the plain delta add; the class
    exists for parity and for server-side instrumentation.
    """

    commit_rule = staticmethod(delta_rule)


class DynSGDParameterServer(ParameterServer):
    """Versioned PS: pull returns the update counter; commits are scaled by
    1/(staleness+1)."""

    commit_rule = staticmethod(dynsgd_rule)

    def __init__(self, params, pull_compress=None):
        super().__init__(params, pull_compress=pull_compress)
        self._meta["version"] = 0

    def _pull_tag(self):
        return self._meta.get("version", 0)


# ------------------------------------------------------- socket (DCN) serving


class SocketParameterServer:
    """Serves a ParameterServer over TCP for cross-host workers.

    Protocol (reference: distkeras/parameter_servers.py ->
    SocketParameterServer.run): connection sends a 1-byte action —
    b"p": pull -> request frame {"worker_id"} -> reply frame {"tag"} + center;
    b"c": commit -> frame {"tag", "commit_id"} + delta, reply b"k";
    b"s": stop the server.
    All frames are the pickle-free JSON-header + npz format from
    ``utils.serialization`` — the reference pickled these payloads, which is
    arbitrary-code-execution on whichever host unpickles them.
    One thread per connection; commits serialize on the PS lock.
    """

    def __init__(self, ps: ParameterServer, host="0.0.0.0", port=0):
        self.ps = ps
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = None
        self._conn_threads = []
        self._running = threading.Event()

    def start(self):
        self.ps.start()
        self._running.set()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        self._listener.settimeout(0.2)
        while self._running.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._conn_threads.append(t)

    def _serve(self, conn: socket.socket):
        try:
            while self._running.is_set():
                action = conn.recv(1)
                if not action:
                    break
                if action == b"p":
                    # pull request: JSON header {"worker_id": ...} (None for
                    # anonymous) — keeps the heartbeat live for remote
                    # workers too. No pickle anywhere on this path.
                    header, _ = unpack_frame(networking.recv_data(conn))
                    center, tag = self.ps.pull(worker_id=header.get("worker_id"))
                    networking.send_data(
                        conn, pack_frame({"tag": tag}, serialize_params(center))
                    )
                elif action == b"c":
                    header, blob = unpack_frame(networking.recv_data(conn))
                    commit_id = header.get("commit_id")
                    if commit_id is not None:
                        commit_id = (commit_id[0], commit_id[1])
                    tree = deserialize_params(blob)
                    local_snap = None
                    if header.get("wrapped"):
                        local_snap = tree.get("snap")
                        tree = tree["delta"]
                    self.ps.commit(
                        tree,
                        header.get("tag"),
                        commit_id=commit_id,
                        local_snap=local_snap,
                    )
                    conn.sendall(b"k")
                elif action == b"s":
                    self.stop()
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._running.clear()
        self.ps.stop()
        try:
            self._listener.close()
        except OSError:
            pass


class RemoteParameterServerClient:
    """Worker-side proxy speaking the socket protocol; drop-in for a local PS."""

    def __init__(self, host: str, port: int, retry=None):
        """``retry``: optional ``networking.RetryPolicy`` used by
        ``reconnect()`` to redial with exponential full-jitter backoff —
        the SAME backoff implementation the serving client uses, so the
        training and serving tiers cannot drift apart on retry
        semantics. A retried worker's PS is often restarting too; a
        policy-paced redial rides out the gap instead of failing the
        whole retry on one refused connection."""
        self.host = host
        self.port = port
        self.retry = retry
        self._sock = networking.connect(host, port)
        self._lock = threading.Lock()

    def reconnect(self):
        """Fresh connection — a retried worker must not reuse a stream that
        may have died mid-message (half-written commit payloads would
        desync the protocol)."""
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            dial = lambda: networking.connect(self.host, self.port)  # noqa: E731
            self._sock = (
                self.retry.call(dial) if self.retry is not None else dial()
            )

    def pull(self, worker_id=None):
        with self._lock:
            self._sock.sendall(b"p")
            networking.send_data(
                self._sock, pack_frame({"worker_id": worker_id})
            )
            header, blob = unpack_frame(networking.recv_data(self._sock))
        return deserialize_params(blob), header.get("tag")

    def commit(self, delta, tag=None, commit_id=None, local_snap=None):
        header = {"tag": tag, "commit_id": list(commit_id) if commit_id else None}
        tree = _to_host(delta)
        if local_snap is not None:
            # worker-local checkpoint state rides the same frame ("wrapped"
            # layout) so remote/DCN workers keep full resume parity with
            # in-process ones; costs one extra params+opt_state per
            # communication window, only when checkpointing is on
            header["wrapped"] = True
            tree = {"delta": tree, "snap": local_snap}
        payload = pack_frame(header, serialize_params(tree))
        with self._lock:
            self._sock.sendall(b"c")
            networking.send_data(self._sock, payload)
            ack = self._sock.recv(1)
        if ack != b"k":
            raise ConnectionError("commit not acknowledged")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
