"""Model layer: Keras-like declarative builder over pure JAX functions.

The reference's examples define models in Keras terms (reference:
examples/mnist.py -> keras.models.Sequential with Dense/Conv2D/Flatten/
Dropout/Activation). This package provides the same builder vocabulary, but a
model compiles down to pure ``init``/``apply`` functions over pytree params —
jit/grad/shard_map-friendly, NHWC layouts, MXU-sized matmuls.
"""

from distkeras_tpu.models.layers import (
    Layer,
    Dense,
    Conv2D,
    MaxPool2D,
    AvgPool2D,
    GlobalAvgPool2D,
    Flatten,
    Dropout,
    Activation,
    BatchNorm,
)
from distkeras_tpu.models.sequential import Sequential, Model
from distkeras_tpu.models import zoo
