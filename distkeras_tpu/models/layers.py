"""Functional layer zoo.

Each layer is a declarative config object with two pure methods:

- ``init(rng, in_shape) -> (params, state, out_shape)``
- ``apply(params, state, x, train, rng) -> (y, new_state)``

``params`` are trainable (a dict pytree), ``state`` is non-trainable (e.g.
BatchNorm moving stats). Both are empty dicts for stateless layers. All apply
functions are jit-traceable with static shapes; convolutions use NHWC/HWIO
layouts so XLA tiles them onto the MXU directly.

Covers the builder vocabulary the reference examples use (reference:
examples/mnist.py — Dense/Conv2D/MaxPooling2D/Flatten/Dropout/Activation)
plus BatchNorm and pooling variants needed for the CIFAR/ResNet configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------- activations

_ACTIVATIONS = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "log_softmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "elu": jax.nn.elu,
    "leaky_relu": jax.nn.leaky_relu,
}


def get_activation(name):
    if name is None:
        return _ACTIVATIONS["linear"]
    if callable(name):
        return name
    if name not in _ACTIVATIONS:
        raise ValueError(f"unknown activation {name!r}")
    return _ACTIVATIONS[name]


# ------------------------------------------------------------------- registry

_LAYER_REGISTRY = {}


def register_layer(cls):
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_config(cfg: dict):
    cfg = dict(cfg)
    cls = _LAYER_REGISTRY[cfg.pop("layer")]
    return cls(**cfg)


# ----------------------------------------------------------------------- init


def _glorot_uniform(rng, shape, fan_in, fan_out, dtype=jnp.float32):
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


# ---------------------------------------------------------------------- base


class Layer:
    """Base declarative layer. Subclasses override init/apply/get_config."""

    # True for layers that consume an rng in train mode (Dropout) — the
    # pipeline trainer's block-run discovery excludes such blocks because
    # the GPipe schedule does not thread per-block rngs
    uses_train_rng = False

    def init(self, rng, in_shape):
        return {}, {}, in_shape

    def apply(self, params, state, x, train=False, rng=None):
        return x, state

    def get_config(self) -> dict:
        return {"layer": type(self).__name__}

    def sublayers(self):
        """Nested Layer children (composite layers override) — lets model
        walkers (e.g. ring-attention attachment) reach every layer."""
        return []

    def __repr__(self):
        cfg = {k: v for k, v in self.get_config().items() if k != "layer"}
        args = ", ".join(f"{k}={v!r}" for k, v in cfg.items())
        return f"{type(self).__name__}({args})"


# --------------------------------------------------------------------- layers


@register_layer
class Dense(Layer):
    """y = act(x @ W + b). Matmul-shaped for the MXU: keep units large/batched."""

    def __init__(self, units, activation=None, use_bias=True):
        self.units = int(units)
        self.activation = activation
        self.use_bias = bool(use_bias)

    def init(self, rng, in_shape):
        fan_in = in_shape[-1]
        params = {
            "kernel": _glorot_uniform(
                rng, (fan_in, self.units), fan_in, self.units
            )
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.units,), jnp.float32)
        return params, {}, (*in_shape[:-1], self.units)

    def apply(self, params, state, x, train=False, rng=None):
        from distkeras_tpu.ops.quantization import qmatmul

        # qmatmul == plain matmul for f32 kernels; int8 weight-only when
        # the tree went through ops.quantization.quantize_params (serving)
        y = qmatmul(x, params["kernel"])
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return get_activation(self.activation)(y), state

    def get_config(self):
        return {
            "layer": "Dense",
            "units": self.units,
            "activation": self.activation,
            "use_bias": self.use_bias,
        }


@register_layer
class Conv2D(Layer):
    """NHWC conv, HWIO kernel — the layout XLA maps onto the MXU."""

    def __init__(
        self,
        filters,
        kernel_size,
        strides=1,
        padding="SAME",
        activation=None,
        use_bias=True,
    ):
        self.filters = int(filters)
        self.kernel_size = (
            (kernel_size, kernel_size)
            if isinstance(kernel_size, int)
            else tuple(kernel_size)
        )
        self.strides = (
            (strides, strides) if isinstance(strides, int) else tuple(strides)
        )
        self.padding = padding
        self.activation = activation
        self.use_bias = bool(use_bias)

    def init(self, rng, in_shape):
        kh, kw = self.kernel_size
        cin = in_shape[-1]
        fan_in = kh * kw * cin
        fan_out = kh * kw * self.filters
        params = {
            "kernel": _glorot_uniform(
                rng, (kh, kw, cin, self.filters), fan_in, fan_out
            )
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,), jnp.float32)
        out_shape = jax.eval_shape(
            lambda x, k: self._conv(x, k),
            jax.ShapeDtypeStruct((1, *in_shape), jnp.float32),
            jax.ShapeDtypeStruct(params["kernel"].shape, jnp.float32),
        ).shape[1:]
        return params, {}, out_shape

    def _conv(self, x, kernel):
        return lax.conv_general_dilated(
            x,
            kernel,
            window_strides=self.strides,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def apply(self, params, state, x, train=False, rng=None):
        y = self._conv(x, params["kernel"].astype(x.dtype))
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return get_activation(self.activation)(y), state

    def get_config(self):
        return {
            "layer": "Conv2D",
            "filters": self.filters,
            "kernel_size": list(self.kernel_size),
            "strides": list(self.strides),
            "padding": self.padding,
            "activation": self.activation,
            "use_bias": self.use_bias,
        }


class _Pool2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding="VALID"):
        self.pool_size = (
            (pool_size, pool_size)
            if isinstance(pool_size, int)
            else tuple(pool_size)
        )
        strides = strides if strides is not None else self.pool_size
        self.strides = (
            (strides, strides) if isinstance(strides, int) else tuple(strides)
        )
        self.padding = padding

    def init(self, rng, in_shape):
        out = jax.eval_shape(
            lambda x: self.apply({}, {}, x)[0],
            jax.ShapeDtypeStruct((1, *in_shape), jnp.float32),
        ).shape[1:]
        return {}, {}, out

    def _window(self, x, init, op):
        return lax.reduce_window(
            x,
            init,
            op,
            window_dimensions=(1, *self.pool_size, 1),
            window_strides=(1, *self.strides, 1),
            padding=self.padding,
        )

    def get_config(self):
        return {
            "layer": type(self).__name__,
            "pool_size": list(self.pool_size),
            "strides": list(self.strides),
            "padding": self.padding,
        }


@register_layer
class MaxPool2D(_Pool2D):
    def apply(self, params, state, x, train=False, rng=None):
        return self._window(x, -jnp.inf, lax.max), state


@register_layer
class AvgPool2D(_Pool2D):
    def apply(self, params, state, x, train=False, rng=None):
        s = self._window(x, 0.0, lax.add)
        return s / (self.pool_size[0] * self.pool_size[1]), state


@register_layer
class GlobalAvgPool2D(Layer):
    def init(self, rng, in_shape):
        return {}, {}, (in_shape[-1],)

    def apply(self, params, state, x, train=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state


@register_layer
class Flatten(Layer):
    def init(self, rng, in_shape):
        size = 1
        for d in in_shape:
            size *= d
        return {}, {}, (size,)

    def apply(self, params, state, x, train=False, rng=None):
        return x.reshape(x.shape[0], -1), state


@register_layer
class Dropout(Layer):
    """Inverted dropout; identity in eval mode. Needs an rng when train=True."""

    uses_train_rng = True

    def __init__(self, rate):
        self.rate = float(rate)

    def apply(self, params, state, x, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout.apply(train=True) requires an rng")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state

    def get_config(self):
        return {"layer": "Dropout", "rate": self.rate}


@register_layer
class Activation(Layer):
    def __init__(self, activation):
        self.activation = activation

    def apply(self, params, state, x, train=False, rng=None):
        return get_activation(self.activation)(x), state

    def get_config(self):
        return {"layer": "Activation", "activation": self.activation}


@register_layer
class Embedding(Layer):
    """Token embedding (+ optional learned positions) for (B, T) int ids.

    No reference counterpart (the reference has no sequence workloads,
    SURVEY §5.7); the entry layer of the rebuild's transformer family.
    """

    def __init__(self, vocab_size, dim, with_positions=True):
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.with_positions = bool(with_positions)

    def init(self, rng, in_shape):
        (t,) = in_shape
        k1, k2 = jax.random.split(rng)
        params = {
            "tokens": 0.02
            * jax.random.normal(k1, (self.vocab_size, self.dim), jnp.float32)
        }
        if self.with_positions:
            params["positions"] = 0.02 * jax.random.normal(
                k2, (t, self.dim), jnp.float32
            )
        return params, {}, (t, self.dim)

    def apply(self, params, state, x, train=False, rng=None):
        y = params["tokens"][x.astype(jnp.int32)]
        if self.with_positions:
            y = y + params["positions"][None, : y.shape[1]]
        return y, state

    def get_config(self):
        return {
            "layer": "Embedding",
            "vocab_size": self.vocab_size,
            "dim": self.dim,
            "with_positions": self.with_positions,
        }


@register_layer
class LayerNorm(Layer):
    """Normalize over the trailing feature axis with learned scale/shift.

    ``norm_fn`` is a process-local hook (same contract as
    ``MultiHeadSelfAttention.attention_fn``): point it at
    ``ops.fused_layernorm.fused_layer_norm`` to run the one-pass Pallas
    kernel instead of the three-pass XLA path. Not serialized — a
    deserialized layer computes the plain path until the receiving
    process re-attaches the hook."""

    def __init__(self, epsilon=1e-5):
        self.epsilon = float(epsilon)
        self.norm_fn = None  # override to plug in the fused kernel

    def init(self, rng, in_shape):
        d = in_shape[-1]
        return (
            {"gamma": jnp.ones((d,), jnp.float32),
             "beta": jnp.zeros((d,), jnp.float32)},
            {},
            in_shape,
        )

    def apply(self, params, state, x, train=False, rng=None):
        if self.norm_fn is not None:
            y = self.norm_fn(x, params["gamma"], params["beta"], self.epsilon)
            return y, state
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * lax.rsqrt(var + self.epsilon)
        y = y * params["gamma"] + params["beta"]
        return y.astype(x.dtype), state

    def get_config(self):
        if self.norm_fn is not None:
            import logging

            logging.getLogger(__name__).warning(
                "LayerNorm.norm_fn is process-local and is not serialized; "
                "the deserialized layer will use the plain XLA path until "
                "the fused kernel is re-attached"
            )
        return {"layer": "LayerNorm", "epsilon": self.epsilon}


@register_layer
class GlobalAvgPool1D(Layer):
    """(B, T, D) -> (B, D): mean over the sequence axis."""

    def init(self, rng, in_shape):
        t, d = in_shape
        return {}, {}, (d,)

    def apply(self, params, state, x, train=False, rng=None):
        return jnp.mean(x, axis=1), state


@register_layer
class MultiHeadSelfAttention(Layer):
    """Multi-head self-attention over (batch, seq, features).

    No reference counterpart (SURVEY §5.7: the reference has no attention);
    this is the long-context building block of the TPU rebuild. On one chip
    it computes dense softmax attention; for sequences sharded across a mesh
    the same math is served by ``parallel.ring_attention.ring_attention``
    (set ``layer.attention_fn`` or use the functional API), which rotates
    K/V blocks over ICI with an online softmax.

    ``attention_fn`` is a process-local hook: it closes over a live Mesh, so
    it is intentionally NOT part of ``get_config`` and does not survive
    serialize_model / from_config — a deserialized layer computes dense
    attention until the receiving process re-attaches its own mesh hook
    (get_config warns when a hook would be dropped).
    """

    def __init__(self, num_heads, head_dim=None, causal=False, use_bias=True):
        self.num_heads = int(num_heads)
        self.head_dim = None if head_dim is None else int(head_dim)
        self.causal = bool(causal)
        self.use_bias = bool(use_bias)
        self.attention_fn = None  # override to plug in ring attention

    def init(self, rng, in_shape):
        t, d = in_shape[-2], in_shape[-1]
        hd = self.head_dim or d // self.num_heads
        if self.head_dim is None and d % self.num_heads:
            raise ValueError(
                f"features {d} not divisible by num_heads {self.num_heads}"
            )
        inner = self.num_heads * hd
        ks = jax.random.split(rng, 4)
        params = {
            name: _glorot_uniform(k, shape, shape[0], shape[1])
            for name, k, shape in [
                ("wq", ks[0], (d, inner)),
                ("wk", ks[1], (d, inner)),
                ("wv", ks[2], (d, inner)),
                ("wo", ks[3], (inner, d)),
            ]
        }
        if self.use_bias:
            params["bo"] = jnp.zeros((d,), jnp.float32)
        return params, {}, (*in_shape[:-1], d)

    def apply(self, params, state, x, train=False, rng=None):
        from distkeras_tpu.ops.quantization import qmatmul, qshape
        from distkeras_tpu.parallel.ring_attention import dense_attention

        b, t, d = x.shape
        h = self.num_heads
        hd = qshape(params["wq"])[1] // h

        def proj(w):
            return qmatmul(x, w).reshape(b, t, h, hd)

        q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
        attn = self.attention_fn or dense_attention
        o = attn(q, k, v, causal=self.causal)
        o = qmatmul(o.reshape(b, t, h * hd), params["wo"])
        if self.use_bias:
            o = o + params["bo"].astype(x.dtype)
        return o, state

    def get_config(self):
        if self.attention_fn is not None:
            import logging

            logging.getLogger(__name__).warning(
                "MultiHeadSelfAttention.attention_fn is process-local and is "
                "not serialized; the deserialized layer will use dense "
                "attention until a mesh hook is re-attached"
            )
        return {
            "layer": "MultiHeadSelfAttention",
            "num_heads": self.num_heads,
            "head_dim": self.head_dim,
            "causal": self.causal,
            "use_bias": self.use_bias,
        }


@register_layer
class TransformerBlock(Layer):
    """Pre-LN transformer block: x + MHSA(LN(x)), then x + MLP(LN(x)).

    The MLP is Dense(mlp_ratio*d, gelu) -> Dense(d). Composes the rebuild's
    long-context vocabulary: with ``parallel.ring_attention`` attached to
    the inner attention (see ``attach_ring_attention``) the block runs with
    the sequence axis sharded over a mesh.

    ``remat=True`` wraps the block in ``jax.checkpoint``: the backward pass
    recomputes the block's activations instead of holding them through the
    whole forward — activation memory drops from O(depth) blocks to O(1)
    per block at ~1/3 extra FLOPs, the standard TPU HBM<->FLOPs trade that
    makes deep/long-sequence training fit. Numerics are unchanged (pinned
    by test). No reference counterpart (the reference has no attention and
    delegates memory to the Keras backend).

    ``dropout`` applies inverted residual dropout to the attention and MLP
    branch outputs in train mode (identity in eval; rng required when
    live). A dropout block consumes the train rng, so pipeline towers
    exclude it (``uses_train_rng``).
    """

    def __init__(self, num_heads, mlp_ratio=4, causal=False, remat=False,
                 dropout=0.0):
        self.num_heads = int(num_heads)
        self.mlp_ratio = int(mlp_ratio)
        self.causal = bool(causal)
        self.remat = bool(remat)
        self.dropout = float(dropout)
        # rng-consuming blocks are excluded from pipeline towers
        # (trainers._find_block_run) — declare only when dropout is live
        self.uses_train_rng = self.dropout > 0.0
        self.mhsa = MultiHeadSelfAttention(self.num_heads, causal=self.causal)
        self.ln1 = LayerNorm()
        self.ln2 = LayerNorm()
        self._fc1 = None  # built in init (needs d)
        self._fc2 = None

    def sublayers(self):
        parts = [self.mhsa, self.ln1, self.ln2]
        if self._fc1 is not None:
            parts += [self._fc1, self._fc2]
        return parts

    def init(self, rng, in_shape):
        t, d = in_shape
        self._fc1 = Dense(self.mlp_ratio * d, activation="gelu")
        self._fc2 = Dense(d)
        ks = jax.random.split(rng, 5)
        params, state = {}, {}
        for name, layer, k, shape in [
            ("ln1", self.ln1, ks[0], in_shape),
            ("mhsa", self.mhsa, ks[1], in_shape),
            ("ln2", self.ln2, ks[2], in_shape),
            ("fc1", self._fc1, ks[3], in_shape),
        ]:
            p, s, out_shape = layer.init(k, shape)
            params[name], state[name] = p, s
        p, s, _ = self._fc2.init(ks[4], (t, self.mlp_ratio * d))
        params["fc2"], state["fc2"] = p, s
        return params, state, in_shape

    def apply(self, params, state, x, train=False, rng=None):
        if self.remat:
            import functools

            fn = jax.checkpoint(functools.partial(self._apply, train=train))
            return fn(params, state, x, rng)
        return self._apply(params, state, x, rng, train=train)

    def _apply(self, params, state, x, rng, train=False):
        drop = train and self.dropout > 0.0
        if drop:
            if rng is None:
                raise ValueError(
                    "TransformerBlock(dropout>0).apply(train=True) "
                    "requires an rng"
                )
            r1, r2 = jax.random.split(rng)
        # reuse the Dropout layer's mask logic (stateless, param-free) so
        # the two inverted-dropout implementations cannot drift
        _dropper = Dropout(self.dropout)

        def residual_drop(h, r):
            if not drop:
                return h
            return _dropper.apply({}, {}, h, train=True, rng=r)[0]

        new_state = dict(state)
        h, new_state["ln1"] = self.ln1.apply(params["ln1"], state["ln1"], x)
        a, new_state["mhsa"] = self.mhsa.apply(
            params["mhsa"], state["mhsa"], h, train, rng
        )
        x = x + residual_drop(a, r1 if drop else None)
        h, new_state["ln2"] = self.ln2.apply(params["ln2"], state["ln2"], x)
        h, new_state["fc1"] = self._fc1.apply(params["fc1"], state["fc1"], h)
        h, new_state["fc2"] = self._fc2.apply(params["fc2"], state["fc2"], h)
        return x + residual_drop(h, r2 if drop else None), new_state

    def get_config(self):
        return {
            "layer": "TransformerBlock",
            "num_heads": self.num_heads,
            "mlp_ratio": self.mlp_ratio,
            "causal": self.causal,
            "remat": self.remat,
            "dropout": self.dropout,
        }


@register_layer
class BatchNorm(Layer):
    """Batch normalization over all but the channel axis.

    Train mode normalizes with batch statistics and updates moving stats in
    ``state``; eval mode uses the moving stats. Functional state threading —
    no in-place mutation — keeps this jit/shard_map-safe. Under the sync
    data-parallel trainer the whole step is one jitted program over a GSPMD-
    sharded batch, so ``jnp.mean``/``jnp.var`` here reduce over the GLOBAL
    batch — XLA inserts the cross-device collective — and every replica holds
    identical moving stats (sync-BatchNorm semantics; pinned by
    tests/test_trainers_sync.py::test_sync_batchnorm_global_batch_stats).
    """

    def __init__(self, momentum=0.99, epsilon=1e-5, scale=True, center=True):
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self.scale = bool(scale)
        self.center = bool(center)

    def init(self, rng, in_shape):
        c = in_shape[-1]
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((c,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((c,), jnp.float32)
        state = {
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32),
        }
        return params, state, in_shape

    def apply(self, params, state, x, train=False, rng=None):
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = jnp.mean(x.astype(jnp.float32), axis=axes)
            var = jnp.var(x.astype(jnp.float32), axis=axes)
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1 - m) * mean,
                "var": m * state["var"] + (1 - m) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.epsilon)
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
        if self.scale:
            y = y * params["gamma"].astype(x.dtype)
        if self.center:
            y = y + params["beta"].astype(x.dtype)
        return y, new_state

    def get_config(self):
        return {
            "layer": "BatchNorm",
            "momentum": self.momentum,
            "epsilon": self.epsilon,
            "scale": self.scale,
            "center": self.center,
        }
