"""Model zoo: the architectures behind the five BASELINE configs.

1. ``mnist_mlp``   — SingleTrainer anchor (reference: examples/mnist.py MLP)
2. ``mnist_cnn``   — DOWNPOUR config and the north-star benchmark model
3. ``higgs_mlp``   — AEASGD ATLAS-Higgs tabular classifier
   (reference: examples/workflow.ipynb)
4. ``cifar10_cnn`` — ADAG config
5. ``resnet18``    — DynSGD / ImageNet scale config

All NHWC, float32 params; trainers may run compute in bfloat16.
"""

from __future__ import annotations

from distkeras_tpu.models.layers import (
    Activation,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    GlobalAvgPool2D,
    MaxPool2D,
)
from distkeras_tpu.models.sequential import Residual, Sequential


def _scaled(channels: int, width: float) -> int:
    """Channel count under a width multiplier, floored at 8 so narrow smoke
    variants keep every layer trainable (and TPU-lane friendly)."""
    return max(8, int(channels * width))


def mnist_mlp(hidden=500, num_classes=10, seed=0):
    """MLP over flattened 28x28 inputs (input shape (784,))."""
    return Sequential(
        [
            Dense(hidden, activation="relu"),
            Dense(hidden, activation="relu"),
            Dense(num_classes, activation="softmax"),
        ]
    ).build((784,), seed=seed)


def mnist_cnn(num_classes=10, seed=0, width=1.0):
    """Small convnet over (28, 28, 1) images — the north-star bench model.

    ``width``: channel multiplier (conv FLOPs scale ~width^2). The benchmark
    matrix's smoke scale passes <1.0 so a 1-core CPU sandbox can afford the
    epochs-to-target axis; chip captures and the full scale keep 1.0."""
    w = lambda c: _scaled(c, width)
    return Sequential(
        [
            Conv2D(w(32), 3, activation="relu", padding="SAME"),
            Conv2D(w(32), 3, activation="relu", padding="SAME"),
            MaxPool2D(2),
            Conv2D(w(64), 3, activation="relu", padding="SAME"),
            Conv2D(w(64), 3, activation="relu", padding="SAME"),
            MaxPool2D(2),
            Flatten(),
            Dense(w(256), activation="relu"),
            Dropout(0.5),
            Dense(num_classes, activation="softmax"),
        ]
    ).build((28, 28, 1), seed=seed)


def digits_mlp(hidden=64, num_classes=10, seed=0):
    """MLP over the REAL 8x8 handwritten-digit set shipped in-repo
    (``data.loaders.digits`` — flattened 64-pixel inputs). The real-data
    acceptance model: its accuracy numbers are measured against data the
    builder did not design (VERDICT r2 missing #1)."""
    return Sequential(
        [
            Dense(hidden, activation="relu"),
            Dense(hidden, activation="relu"),
            Dense(num_classes, activation="softmax"),
        ]
    ).build((64,), seed=seed)


def tabular_regressor(num_features=10, hidden=64, seed=0):
    """MLP regressor with a linear (B, 1) output head — the regression
    face of the reference's arbitrary-model support (reference:
    distkeras/trainers.py accepts whatever compiled Keras model the user
    hands it, regressors included). Pairs with ``loss="mse"``/"mae" and
    ``RSquaredEvaluator``; the real acceptance data is
    ``loaders.diabetes()``."""
    return Sequential(
        [
            Dense(hidden, activation="relu"),
            Dense(hidden, activation="relu"),
            Dense(1),
        ]
    ).build((num_features,), seed=seed)


def higgs_mlp(num_features=30, hidden=600, num_classes=2, seed=0):
    """ATLAS-Higgs-style tabular classifier (wide MLP over ~30 features)."""
    return Sequential(
        [
            Dense(hidden, activation="relu"),
            Dropout(0.3),
            Dense(hidden, activation="relu"),
            Dropout(0.3),
            Dense(num_classes, activation="softmax"),
        ]
    ).build((num_features,), seed=seed)


def cifar10_cnn(num_classes=10, seed=0, bn_momentum=0.99, width=1.0):
    """VGG-ish convnet over (32, 32, 3).

    ``bn_momentum``: BatchNorm moving-stats momentum. The 0.99 default needs
    hundreds of steps before eval-mode stats track the batch stats; short
    runs (benchmark smoke epochs) should pass ~0.9.
    ``width``: channel multiplier — see :func:`mnist_cnn`."""
    bn = lambda: BatchNorm(momentum=bn_momentum)
    w = lambda c: _scaled(c, width)
    return Sequential(
        [
            Conv2D(w(64), 3, padding="SAME", use_bias=False),
            bn(),
            Activation("relu"),
            Conv2D(w(64), 3, padding="SAME", use_bias=False),
            bn(),
            Activation("relu"),
            MaxPool2D(2),
            Conv2D(w(128), 3, padding="SAME", use_bias=False),
            bn(),
            Activation("relu"),
            Conv2D(w(128), 3, padding="SAME", use_bias=False),
            bn(),
            Activation("relu"),
            MaxPool2D(2),
            Flatten(),
            Dense(w(256), activation="relu"),
            Dropout(0.5),
            Dense(num_classes, activation="softmax"),
        ]
    ).build((32, 32, 3), seed=seed)


def transformer_classifier(
    vocab_size=64,
    seq_len=64,
    d_model=64,
    num_heads=4,
    depth=2,
    num_classes=2,
    seed=0,
    remat=False,
):
    """Sequence classifier: Embedding -> TransformerBlock xN -> mean-pool
    -> softmax head. No reference counterpart (SURVEY §5.7: no attention
    upstream); the rebuild's long-context model family. Pair with
    ``parallel.ring_attention.attach_ring_attention`` to shard the sequence
    axis over a mesh; ``remat=True`` checkpoints each block so activation
    memory stays O(1) in depth (the long-context HBM trade)."""
    from distkeras_tpu.models.layers import (
        Dense,
        Embedding,
        GlobalAvgPool1D,
        LayerNorm,
        TransformerBlock,
    )
    from distkeras_tpu.models.sequential import Sequential

    model = Sequential(
        [
            Embedding(vocab_size, d_model),
            *[TransformerBlock(num_heads, remat=remat) for _ in range(depth)],
            LayerNorm(),
            GlobalAvgPool1D(),
            Dense(num_classes, activation="softmax"),
        ]
    )
    model.build((seq_len,), seed=seed)
    return model


def transformer_lm(
    vocab_size=256,
    seq_len=128,
    d_model=128,
    num_heads=4,
    depth=2,
    seed=0,
    remat=False,
    dropout=0.0,
):
    """Causal language model: Embedding -> causal TransformerBlock xN ->
    LayerNorm -> logits over the vocabulary (no softmax; pair with the
    ``next_token_crossentropy`` loss, which shifts targets by one). No
    reference counterpart (SURVEY §5.7: no sequence models upstream); this
    is the rebuild's autoregressive long-context family — causal blocks
    compose with ``attach_flash_attention`` (masked-block skipping),
    ``attach_blockwise_attention``, and the ring-attention SP trainer the
    same way the classifier does."""
    from distkeras_tpu.models.layers import (
        Dense,
        Embedding,
        LayerNorm,
        TransformerBlock,
    )
    from distkeras_tpu.models.sequential import Sequential

    model = Sequential(
        [
            Embedding(vocab_size, d_model),
            *[
                TransformerBlock(num_heads, causal=True, remat=remat,
                                 dropout=dropout)
                for _ in range(depth)
            ],
            LayerNorm(),
            Dense(vocab_size),
        ]
    )
    model.build((seq_len,), seed=seed)
    return model


def moe_transformer_lm(
    vocab_size=256,
    seq_len=128,
    d_model=128,
    num_heads=4,
    depth=2,
    num_experts=8,
    seed=0,
    remat=False,
):
    """Causal language model with switch-MoE feed-forwards after each
    block — the expert-parallel autoregressive family. Within a row,
    causality is preserved: routing mixes no information across tokens,
    and the capacity cumsum's priority is positional, so a position's
    keep/drop never depends on later tokens. (Capacity is a global
    budget, though — whether a token is dropped can depend on the OTHER
    rows in the batch, so eval logits are batch-composition-dependent,
    as in any capacity-dropped switch MoE.) Pair with
    ``next_token_crossentropy`` and
    ``parallel.expert_parallel.attach_expert_mesh`` to shard the experts.
    No reference counterpart (SURVEY §3.3/§5.7)."""
    from distkeras_tpu.models.layers import (
        Dense,
        Embedding,
        LayerNorm,
        TransformerBlock,
    )
    from distkeras_tpu.models.sequential import Sequential
    from distkeras_tpu.parallel.expert_parallel import MoE

    layers = [Embedding(vocab_size, d_model)]
    for _ in range(depth):
        layers += [
            TransformerBlock(num_heads, causal=True, remat=remat),
            MoE(num_experts),
        ]
    layers += [LayerNorm(), Dense(vocab_size)]
    model = Sequential(layers)
    model.build((seq_len,), seed=seed)
    return model


def moe_transformer_classifier(
    vocab_size=64,
    seq_len=64,
    d_model=64,
    num_heads=4,
    depth=2,
    num_experts=8,
    num_classes=2,
    seed=0,
):
    """Sequence classifier with switch-MoE feed-forwards after each
    transformer block — the expert-parallel model family. Pair with
    ``parallel.expert_parallel.attach_expert_mesh`` to shard the experts
    over a mesh (GSPMD inserts the token<->expert all-to-all); the MoE
    load-balance aux loss reaches the training loss through WorkerCore's
    aux_loss_weight. No reference counterpart (SURVEY §3.3: EP absent
    upstream)."""
    from distkeras_tpu.models.layers import (
        Dense,
        Embedding,
        GlobalAvgPool1D,
        LayerNorm,
        TransformerBlock,
    )
    from distkeras_tpu.models.sequential import Sequential
    from distkeras_tpu.parallel.expert_parallel import MoE

    layers = [Embedding(vocab_size, d_model)]
    for _ in range(depth):
        layers += [TransformerBlock(num_heads), MoE(num_experts)]
    layers += [LayerNorm(), GlobalAvgPool1D(), Dense(num_classes, activation="softmax")]
    model = Sequential(layers)
    model.build((seq_len,), seed=seed)
    return model


def _basic_block(filters, stride=1, downsample=False, bn_momentum=0.99):
    bn = lambda: BatchNorm(momentum=bn_momentum)
    shortcut = (
        [Conv2D(filters, 1, strides=stride, padding="SAME", use_bias=False), bn()]
        if downsample
        else None
    )
    return Residual(
        [
            Conv2D(filters, 3, strides=stride, padding="SAME", use_bias=False),
            bn(),
            Activation("relu"),
            Conv2D(filters, 3, padding="SAME", use_bias=False),
            bn(),
        ],
        shortcut=shortcut,
        activation="relu",
    )


def resnet18(
    num_classes=1000, input_shape=(224, 224, 3), small_stem=False, seed=0,
    bn_momentum=0.99, width=1.0,
):
    """ResNet-18 (NHWC). ``small_stem=True`` swaps the 7x7/s2+maxpool stem for
    a 3x3/s1 stem, the standard CIFAR-scale variant used in smoke tests.
    ``bn_momentum``: see :func:`cifar10_cnn`.
    ``width``: filter multiplier over the whole trunk (same 18-layer
    topology); see :func:`mnist_cnn` for why the benchmark smoke scale
    shrinks it."""
    bn = lambda: BatchNorm(momentum=bn_momentum)
    w = lambda c: _scaled(c, width)
    stem = (
        [Conv2D(w(64), 3, strides=1, padding="SAME", use_bias=False), bn(), Activation("relu")]
        if small_stem
        else [
            Conv2D(w(64), 7, strides=2, padding="SAME", use_bias=False),
            bn(),
            Activation("relu"),
            MaxPool2D(3, strides=2, padding="SAME"),
        ]
    )
    blk = lambda *a, **kw: _basic_block(*a, bn_momentum=bn_momentum, **kw)
    body = [
        blk(w(64)),
        blk(w(64)),
        blk(w(128), stride=2, downsample=True),
        blk(w(128)),
        blk(w(256), stride=2, downsample=True),
        blk(w(256)),
        blk(w(512), stride=2, downsample=True),
        blk(w(512)),
    ]
    head = [GlobalAvgPool2D(), Dense(num_classes, activation="softmax")]
    return Sequential(stem + body + head).build(input_shape, seed=seed)


ZOO = {
    "mnist_mlp": mnist_mlp,
    "mnist_cnn": mnist_cnn,
    "higgs_mlp": higgs_mlp,
    "cifar10_cnn": cifar10_cnn,
    "resnet18": resnet18,
    "transformer_classifier": transformer_classifier,
    "moe_transformer_classifier": moe_transformer_classifier,
}
