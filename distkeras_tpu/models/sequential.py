"""Sequential model container + Residual composite block.

Keras-like surface (reference: examples/mnist.py builds
``keras.models.Sequential`` and the trainers carry it around serialized;
reference: distkeras/utils.py -> serialize_keras_model). A ``Sequential``
here is a declarative layer list that, once ``build(input_shape)`` is called,
exposes:

- ``model.params`` / ``model.state`` — pytrees (dicts keyed "0", "1", ...)
- ``model.apply(params, state, x, train, rng) -> (y, new_state)`` — a pure
  function safe to close over in jit/grad/shard_map
- ``get_weights()/set_weights()`` — flat ndarray lists, the reference's wire
  format for PS pull/commit payloads

``Residual`` adds the skip-connection vocabulary needed for ResNet-18
(BASELINE config 5) while staying inside the declarative-config world.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.layers import (
    Layer,
    get_activation,
    layer_from_config,
    register_layer,
)


@register_layer
class Residual(Layer):
    """y = act(main(x) + shortcut(x)); shortcut defaults to identity."""

    def __init__(self, layers, shortcut=None, activation="relu"):
        self.layers = [
            l if isinstance(l, Layer) else layer_from_config(l) for l in layers
        ]
        self.shortcut = [
            l if isinstance(l, Layer) else layer_from_config(l)
            for l in (shortcut or [])
        ]
        self.activation = activation

    def init(self, rng, in_shape):
        params, state = {}, {}
        shape = in_shape
        rngs = jax.random.split(rng, len(self.layers) + len(self.shortcut) + 1)
        for i, layer in enumerate(self.layers):
            p, s, shape = layer.init(rngs[i], shape)
            params[f"main_{i}"] = p
            state[f"main_{i}"] = s
        sshape = in_shape
        for i, layer in enumerate(self.shortcut):
            p, s, sshape = layer.init(rngs[len(self.layers) + i], sshape)
            params[f"short_{i}"] = p
            state[f"short_{i}"] = s
        if sshape != shape:
            raise ValueError(
                f"Residual branch shapes differ: main {shape} vs shortcut {sshape}"
            )
        return params, state, shape

    def apply(self, params, state, x, train=False, rng=None):
        rngs = (
            jax.random.split(rng, len(self.layers) + len(self.shortcut))
            if rng is not None
            else [None] * (len(self.layers) + len(self.shortcut))
        )
        new_state = {}
        y = x
        for i, layer in enumerate(self.layers):
            y, new_state[f"main_{i}"] = layer.apply(
                params[f"main_{i}"], state[f"main_{i}"], y, train, rngs[i]
            )
        s = x
        for i, layer in enumerate(self.shortcut):
            s, new_state[f"short_{i}"] = layer.apply(
                params[f"short_{i}"],
                state[f"short_{i}"],
                s,
                train,
                rngs[len(self.layers) + i],
            )
        return get_activation(self.activation)(y + s), new_state

    def get_config(self):
        return {
            "layer": "Residual",
            "layers": [l.get_config() for l in self.layers],
            "shortcut": [l.get_config() for l in self.shortcut],
            "activation": self.activation,
        }

    def sublayers(self):
        return list(self.layers) + list(self.shortcut)


def walk_layers(model_or_layers):
    """Depth-first generator over a model's layers including sublayers —
    THE traversal for mesh-hook attach/detach helpers (ring attention, MoE)
    so they cannot diverge."""
    stack = list(getattr(model_or_layers, "layers", model_or_layers))
    while stack:
        layer = stack.pop()
        yield layer
        stack.extend(layer.sublayers())


class Model:
    """Built model handle: (apply_fn, params, state) + Keras-ish conveniences."""

    def __init__(self, layers, input_shape, params, state):
        self.layers = layers
        self.input_shape = tuple(input_shape)
        self.params = params
        self.state = state

    # -- pure function ------------------------------------------------------

    def apply(self, params, state, x, train=False, rng=None):
        rngs = (
            jax.random.split(rng, len(self.layers))
            if rng is not None
            else [None] * len(self.layers)
        )
        new_state = {}
        for i, layer in enumerate(self.layers):
            x, new_state[str(i)] = layer.apply(
                params[str(i)], state[str(i)], x, train, rngs[i]
            )
        return x, new_state

    def __call__(self, x, train=False, rng=None):
        y, _ = self.apply(self.params, self.state, x, train=train, rng=rng)
        return y

    def predict(self, x, batch_size=None):
        """Jit-compiled batched inference on the current params."""
        fn = getattr(self, "_predict_fn", None)
        if fn is None:
            fn = jax.jit(lambda p, s, xb: self.apply(p, s, xb, train=False)[0])
            self._predict_fn = fn
        x = jnp.asarray(x)
        if batch_size is None or x.shape[0] <= batch_size:
            return np.asarray(fn(self.params, self.state, x))
        outs = [
            np.asarray(fn(self.params, self.state, x[i : i + batch_size]))
            for i in range(0, x.shape[0], batch_size)
        ]
        return np.concatenate(outs, axis=0)

    # -- weights ------------------------------------------------------------

    def get_weights(self):
        """Flat list of ndarrays in deterministic tree order (PS wire format)."""
        return [np.asarray(w) for w in jax.tree.leaves(self.params)]

    def set_weights(self, weights):
        leaves, treedef = jax.tree.flatten(self.params)
        if len(weights) != len(leaves):
            raise ValueError(
                f"expected {len(leaves)} weight arrays, got {len(weights)}"
            )
        new = [
            jnp.asarray(w, dtype=old.dtype).reshape(old.shape)
            for old, w in zip(leaves, weights)
        ]
        self.params = jax.tree.unflatten(treedef, new)

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self.params))

    # -- config -------------------------------------------------------------

    def get_config(self):
        return [l.get_config() for l in self.layers]

    def copy(self) -> "Model":
        return Model(
            self.layers,
            self.input_shape,
            jax.tree.map(lambda a: a, self.params),
            jax.tree.map(lambda a: a, self.state),
        )

    def summary(self) -> str:
        lines = [f"Model(input_shape={self.input_shape})"]
        for i, layer in enumerate(self.layers):
            n = sum(
                int(np.prod(l.shape))
                for l in jax.tree.leaves(self.params[str(i)])
            )
            lines.append(f"  {i}: {layer!r}  params={n}")
        lines.append(f"total params: {self.num_params()}")
        return "\n".join(lines)


class Sequential(Model):
    """Declarative layer stack; call ``build(input_shape)`` to materialize."""

    def __init__(self, layers=None):
        self.layers = list(layers or [])
        self.input_shape = None
        self.params = None
        self.state = None

    def add(self, layer: Layer):
        self.layers.append(layer)

    def build(self, input_shape, seed=0):
        """input_shape excludes the batch dim, e.g. (784,) or (28, 28, 1)."""
        self.input_shape = tuple(int(d) for d in input_shape)
        rng = jax.random.PRNGKey(seed)
        rngs = jax.random.split(rng, max(1, len(self.layers)))
        params, state = {}, {}
        shape = self.input_shape
        for i, layer in enumerate(self.layers):
            p, s, shape = layer.init(rngs[i], shape)
            params[str(i)] = p
            state[str(i)] = s
        self.output_shape = shape
        self.params = params
        self.state = state
        return self

    @classmethod
    def from_config(cls, configs) -> "Sequential":
        return cls([layer_from_config(c) for c in configs])

    def copy(self) -> "Sequential":
        m = Sequential(self.layers)
        m.input_shape = self.input_shape
        if self.params is not None:
            m.output_shape = self.output_shape
            m.params = jax.tree.map(lambda a: a, self.params)
            m.state = jax.tree.map(lambda a: a, self.state)
        return m
