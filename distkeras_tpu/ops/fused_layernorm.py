"""Fused LayerNorm — one-pass Pallas TPU kernels, forward AND backward.

After FlashAttention (``ops/flash_attention.py``) the transformer's
remaining bandwidth-bound hot op is LayerNorm: the XLA path reads the
activation once for the mean, again for the variance, and a third time to
normalize, with the (B, T, D) tensor round-tripping HBM between passes.
These kernels compute mean/var/normalize/affine in ONE VMEM pass per row
block; the backward kernel recomputes the row statistics from x instead of
saving them, so nothing but (x, gamma) is carried between passes and the
1-D per-row stats never touch HBM at all.

No reference counterpart (the reference has no normalization layers beyond
BatchNorm and no attention workloads — SURVEY §3.3/§5.7); this is
performance tier for the rebuild's transformer family. Numerics match
``models.layers.LayerNorm.apply`` (f32 compute, biased variance, output
cast back to the input dtype).

Layout: x flattens to (rows, D) and tiles over row blocks; gamma/beta ride
along as a replicated (1, D) block. dgamma/dbeta come out of the backward
kernel as per-block partial sums, reduced in XLA. Requires D % 128 == 0
(lane width) — other widths take the plain jnp path, as do rows that
don't fill one sublane tile. Falls back to interpreter mode off TPU (the
8-device CPU test mesh), chosen at trace time like the other kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANE = 128
DEFAULT_BLOCK_ROWS = 256
# x, dy, dx blocks live in VMEM together (f32); stay well under ~16 MB/core
_VMEM_ROW_BUDGET_BYTES = 4 * 1024 * 1024


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _reference_layer_norm(x, gamma, beta, epsilon):
    """The plain-XLA path — identical math to LayerNorm.apply."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
    return (y * gamma + beta).astype(x.dtype)


def _block_rows_for(n_rows: int, d: int) -> int:
    """Sublane-aligned row-block height under the VMEM budget."""
    budget = max(8, _VMEM_ROW_BUDGET_BYTES // (3 * d * 4))
    rows = min(DEFAULT_BLOCK_ROWS, budget, int(np.ceil(n_rows / 8)) * 8)
    return max(8, (rows // 8) * 8)


# ----------------------------------------------------------------- kernels


def _fwd_kernel(eps, x_ref, g_ref, b_ref, y_ref):
    x = x_ref[:].astype(jnp.float32)  # (rows, D)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y_ref[:] = (y * g_ref[:] + b_ref[:]).astype(y_ref.dtype)


def _bwd_kernel(eps, x_ref, g_ref, dy_ref, dx_ref, dg_ref, db_ref):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    a = dy * g_ref[:]
    m1 = jnp.mean(a, axis=1, keepdims=True)
    m2 = jnp.mean(a * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (a - m1 - xhat * m2)).astype(dx_ref.dtype)
    # per-block partial sums; XLA reduces the block axis afterwards. The
    # refs are (1, 1, d) blocks — see _bwd's layout note on why the block
    # axis needs its own leading dim on real TPU.
    dg_ref[:] = jnp.sum(dy * xhat, axis=0, keepdims=True)[None]
    db_ref[:] = jnp.sum(dy, axis=0, keepdims=True)[None]


def _pad_rows(mat, block_rows):
    n = mat.shape[0]
    padded = int(np.ceil(n / block_rows)) * block_rows
    if padded != n:
        mat = jnp.pad(mat, ((0, padded - n), (0, 0)))
    return mat


def _row_specs(num, block_rows, d):
    return [
        pl.BlockSpec((block_rows, d), lambda i: (i, 0)) for _ in range(num)
    ]


def _vec_spec(d):
    return pl.BlockSpec((1, d), lambda i: (0, 0))


def _fwd(x2, gamma, beta, eps, block_rows, interpret):
    n, d = x2.shape
    xp = _pad_rows(x2, block_rows)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, eps),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x2.dtype),
        grid=(xp.shape[0] // block_rows,),
        in_specs=_row_specs(1, block_rows, d) + [_vec_spec(d), _vec_spec(d)],
        out_specs=_row_specs(1, block_rows, d)[0],
        interpret=interpret,
    )(xp, gamma.astype(jnp.float32)[None], beta.astype(jnp.float32)[None])
    return y[:n]


def _bwd(x2, gamma, dy2, eps, block_rows, interpret):
    n, d = x2.shape
    xp = _pad_rows(x2, block_rows)
    dyp = _pad_rows(dy2, block_rows)  # zero rows: zero dx and zero partials
    nblocks = xp.shape[0] // block_rows
    # dgamma/dbeta partials are (nblocks, 1, d) with (1, 1, d) blocks:
    # Mosaic requires a block's last two dims divisible by (8, 128) or
    # equal to the array's — a (1, d) block on a (nblocks, d) array has
    # block[-2] == 1 != nblocks and fails to lower on real TPU (the CPU
    # interpreter never checks). With the block axis leading, the last two
    # dims are (1, d) == the array's own (1, d).
    dx, dg_part, db_part = pl.pallas_call(
        functools.partial(_bwd_kernel, eps),
        out_shape=(
            jax.ShapeDtypeStruct(xp.shape, x2.dtype),
            jax.ShapeDtypeStruct((nblocks, 1, d), jnp.float32),
            jax.ShapeDtypeStruct((nblocks, 1, d), jnp.float32),
        ),
        grid=(nblocks,),
        in_specs=_row_specs(1, block_rows, d)
        + [_vec_spec(d)]
        + _row_specs(1, block_rows, d),
        out_specs=(
            _row_specs(1, block_rows, d)[0],
            pl.BlockSpec((1, 1, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, d), lambda i: (i, 0, 0)),
        ),
        interpret=interpret,
    )(xp, gamma.astype(jnp.float32)[None], dyp)
    return dx[:n], jnp.sum(dg_part, axis=(0, 1)), jnp.sum(db_part, axis=(0, 1))


# -------------------------------------------------------------- custom VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused(x2, gamma, beta, eps, block_rows, interpret):
    return _fwd(x2, gamma, beta, eps, block_rows, interpret)


def _fused_fwd(x2, gamma, beta, eps, block_rows, interpret):
    # beta rides the residuals only for its dtype: the cotangent must match
    # the primal's dtype even when gamma and beta dtypes differ
    return _fwd(x2, gamma, beta, eps, block_rows, interpret), (x2, gamma, beta)


def _fused_bwd(eps, block_rows, interpret, residuals, dy2):
    x2, gamma, beta = residuals
    dx, dg, db = _bwd(x2, gamma, dy2, eps, block_rows, interpret)
    return dx, dg.astype(gamma.dtype), db.astype(beta.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_layer_norm(x, gamma, beta, epsilon=1e-5):
    """LayerNorm over the trailing axis in one fused pass.

    ``x``: (..., D); ``gamma``/``beta``: (D,). Matches
    ``models.layers.LayerNorm.apply`` numerics (f32 compute, biased
    variance, result cast to x.dtype). Widths that don't tile the 128-wide
    lanes — or tiny inputs where a kernel launch costs more than it saves —
    take the identical-math XLA path instead.
    """
    d = x.shape[-1]
    n_rows = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1
    if d % LANE or x.ndim < 2 or n_rows < 8:
        return _reference_layer_norm(x, gamma, beta, epsilon)
    x2 = x.reshape(n_rows, d)
    block_rows = _block_rows_for(n_rows, d)
    out = _fused(
        x2, gamma, beta, float(epsilon), block_rows, not _on_tpu()
    )
    return out.reshape(x.shape)


def attach_fused_layernorm(model) -> int:
    """Point every LayerNorm at the fused kernel (single-chip fast path).
    Returns how many were attached. Process-local, like the attention
    hooks — not serialized."""
    from distkeras_tpu.models.layers import LayerNorm
    from distkeras_tpu.models.sequential import walk_layers

    n = 0
    for layer in walk_layers(model):
        if isinstance(layer, LayerNorm):
            layer.norm_fn = fused_layer_norm
            n += 1
    return n
