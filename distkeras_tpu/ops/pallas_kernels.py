"""Pallas TPU kernels — the framework's hand-written native tier.

The reference has no native components of its own (SURVEY §3.4); its compute
runs in the Keras backend. Here the equivalent tier is XLA-compiled JAX plus
these Pallas kernels for ops worth owning:

- ``fused_sgd``: the optimizer update applied in ONE pass over each
  parameter buffer (p' = p - lr*u and m' = mu*m + g computed together in
  VMEM), instead of the separate update/apply traffic of the generic
  optax path (reference: the worker optimizer step inside
  distkeras/workers.py -> Worker.train's ``train_on_batch``).
- ``fused_adam``: the full Adam update (both moment EMAs, bias
  correction, rsqrt, and the parameter write) in one VMEM pass per
  buffer. The generic optax path streams p/g/m/v through HBM several
  times (update, then apply_updates); here each block is read once and
  written once. Bias-correction factors depend on the step count, so
  they enter the kernel as a (1, 2) SMEM scalar block instead of being
  baked in like lr/betas/eps.

Kernels compile with Mosaic on TPU and fall back to interpreter mode on
CPU (tests run on the 8-device CPU mesh), chosen at trace time.

Layout: each parameter leaf is raveled and tiled to (rows, 128) f32 blocks
(lane width 128, sublane multiple 8 — see the Pallas TPU guide's tiling
table); leaves smaller than one tile use plain VPU-fused jnp math, where a
kernel launch would cost more than it saves.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
BLOCK_ROWS = 512  # (512, 128) f32 = 256 KiB per buffer — comfortably in VMEM
_MIN_KERNEL_SIZE = 8 * LANE  # below one f32 tile, jnp is cheaper


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _block_rows_for(n: int) -> int:
    """Per-leaf block height: the sublane-aligned row count, capped at
    BLOCK_ROWS — a leaf slightly over one tile pads to its own size, not to
    a full 512-row block (64x waste for small leaves otherwise)."""
    rows = pl.cdiv(n, LANE)
    return min(int(np.ceil(rows / 8)) * 8, BLOCK_ROWS)


def _pad_to_blocks(flat, block_rows):
    """(n,) -> (rows, LANE) with rows a multiple of ``block_rows``."""
    n = flat.shape[0]
    rows = pl.cdiv(n, LANE)
    rows_padded = int(np.ceil(rows / block_rows)) * block_rows
    flat = jnp.pad(flat, (0, rows_padded * LANE - n))
    return flat.reshape(rows_padded, LANE)


def _unpad(mat, shape, dtype):
    n = int(np.prod(shape)) if shape else 1
    return mat.reshape(-1)[:n].reshape(shape).astype(dtype)


# ----------------------------------------------------------------- kernels


def _sgd_kernel(lr, p_ref, g_ref, out_ref):
    out_ref[:] = p_ref[:] - lr * g_ref[:]


def _sgd_momentum_kernel(lr, mu, nesterov, p_ref, g_ref, m_ref, op_ref, om_ref):
    m_new = mu * m_ref[:] + g_ref[:]
    update = g_ref[:] + mu * m_new if nesterov else m_new
    op_ref[:] = p_ref[:] - lr * update
    om_ref[:] = m_new


def _block_specs(num, block_rows):
    return [
        pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
        for _ in range(num)
    ]


def _leaf_sgd(p, g, lr, interpret):
    shape, dtype = p.shape, p.dtype
    if p.size < _MIN_KERNEL_SIZE:
        return (p.astype(jnp.float32) - lr * g.astype(jnp.float32)).astype(dtype)
    br = _block_rows_for(p.size)
    pm = _pad_to_blocks(p.ravel().astype(jnp.float32), br)
    gm = _pad_to_blocks(g.ravel().astype(jnp.float32), br)
    out = pl.pallas_call(
        functools.partial(_sgd_kernel, lr),
        out_shape=jax.ShapeDtypeStruct(pm.shape, jnp.float32),
        grid=(pm.shape[0] // br,),
        in_specs=_block_specs(2, br),
        out_specs=_block_specs(1, br)[0],
        interpret=interpret,
    )(pm, gm)
    return _unpad(out, shape, dtype)


def _leaf_sgd_momentum(p, g, m, lr, mu, nesterov, interpret):
    shape, dtype = p.shape, p.dtype
    if p.size < _MIN_KERNEL_SIZE:
        p32, g32, m32 = (x.astype(jnp.float32) for x in (p, g, m))
        m_new = mu * m32 + g32
        update = g32 + mu * m_new if nesterov else m_new
        return (p32 - lr * update).astype(dtype), m_new
    br = _block_rows_for(p.size)
    pm = _pad_to_blocks(p.ravel().astype(jnp.float32), br)
    gm = _pad_to_blocks(g.ravel().astype(jnp.float32), br)
    mm = _pad_to_blocks(m.ravel().astype(jnp.float32), br)
    op, om = pl.pallas_call(
        functools.partial(_sgd_momentum_kernel, lr, mu, nesterov),
        out_shape=(
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
        ),
        grid=(pm.shape[0] // br,),
        in_specs=_block_specs(3, br),
        out_specs=tuple(_block_specs(2, br)),
        interpret=interpret,
    )(pm, gm, mm)
    return _unpad(op, shape, dtype), _unpad(om, shape, jnp.float32)


def _adam_math(p32, g32, m32, v32, lr, b1, b2, eps, c1, c2):
    """The one copy of the Adam update; both the kernel and the small-leaf
    jnp path call it (c1/c2 are the bias-correction factors 1/(1-b^t))."""
    m_new = b1 * m32 + (1.0 - b1) * g32
    v_new = b2 * v32 + (1.0 - b2) * g32 * g32
    p_new = p32 - lr * (m_new * c1) / (jnp.sqrt(v_new * c2) + eps)
    return p_new, m_new, v_new


def _adam_kernel(lr, b1, b2, eps, c_ref, p_ref, g_ref, m_ref, v_ref,
                 op_ref, om_ref, ov_ref):
    op_ref[:], om_ref[:], ov_ref[:] = _adam_math(
        p_ref[:], g_ref[:], m_ref[:], v_ref[:],
        lr, b1, b2, eps, c_ref[0, 0], c_ref[0, 1],
    )


def _leaf_adam(p, g, m, v, scalars, lr, b1, b2, eps, interpret):
    shape, dtype = p.shape, p.dtype
    if p.size < _MIN_KERNEL_SIZE:
        p32, g32, m32, v32 = (x.astype(jnp.float32) for x in (p, g, m, v))
        c1, c2 = scalars[0, 0], scalars[0, 1]
        p_new, m_new, v_new = _adam_math(
            p32, g32, m32, v32, lr, b1, b2, eps, c1, c2
        )
        return p_new.astype(dtype), m_new, v_new
    br = _block_rows_for(p.size)
    pm = _pad_to_blocks(p.ravel().astype(jnp.float32), br)
    gm = _pad_to_blocks(g.ravel().astype(jnp.float32), br)
    mm = _pad_to_blocks(m.ravel().astype(jnp.float32), br)
    vm = _pad_to_blocks(v.ravel().astype(jnp.float32), br)
    scalar_spec = pl.BlockSpec(
        (1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM
    )
    op, om, ov = pl.pallas_call(
        functools.partial(_adam_kernel, lr, b1, b2, eps),
        out_shape=(
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
        ),
        grid=(pm.shape[0] // br,),
        in_specs=[scalar_spec] + _block_specs(4, br),
        out_specs=tuple(_block_specs(3, br)),
        interpret=interpret,
    )(scalars, pm, gm, mm, vm)
    return (
        _unpad(op, shape, dtype),
        _unpad(om, shape, jnp.float32),
        _unpad(ov, shape, jnp.float32),
    )


# ------------------------------------------------------------ optimizer API


class FusedSGD:
    """Fused-apply optimizer: one VMEM pass computes p' (and m') directly.

    Exposes the ``init``/``fused_apply`` protocol WorkerCore prefers over
    the two-step optax ``update``+``apply_updates`` when present.
    """

    def __init__(self, learning_rate=0.01, momentum=0.0, nesterov=False):
        if callable(learning_rate):
            raise TypeError(
                "pallas_sgd bakes the learning rate into the kernel and "
                "does not accept schedules; use optimizer 'sgd' with a "
                "schedule instead"
            )
        self.learning_rate = float(learning_rate)
        self.momentum = float(momentum)
        self.nesterov = bool(nesterov)

    def init(self, params):
        if self.momentum == 0.0:
            return ()
        return jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )

    def fused_apply(self, params, grads, state):
        interpret = not _on_tpu()
        if self.momentum == 0.0:
            new_params = jax.tree.map(
                lambda p, g: _leaf_sgd(p, g, self.learning_rate, interpret),
                params,
                grads,
            )
            return new_params, state
        out = jax.tree.map(
            lambda p, g, m: _leaf_sgd_momentum(
                p, g, m, self.learning_rate, self.momentum,
                self.nesterov, interpret,
            ),
            params,
            grads,
            state,
        )
        new_params = jax.tree.map(
            lambda pair: pair[0], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        new_state = jax.tree.map(
            lambda pair: pair[1], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        return new_params, new_state


class FusedAdam:
    """Fused-apply Adam: moments, bias correction, and the parameter write
    in one VMEM pass per buffer; numerically matches ``optax.adam``.

    State is ``(m_tree, v_tree, count)`` with ``count`` an int32 step
    counter (optax convention: first apply uses t = 1). Bias-correction
    factors 1/(1-b^t) are traced scalars, shipped to the kernel as a
    (1, 2) SMEM block.
    """

    def __init__(self, learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8):
        if callable(learning_rate):
            raise TypeError(
                "pallas_adam bakes the learning rate into the kernel and "
                "does not accept schedules; use optimizer 'adam' with a "
                "schedule instead"
            )
        self.learning_rate = float(learning_rate)
        self.b1 = float(b1)
        self.b2 = float(b2)
        self.eps = float(eps)

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return (
            jax.tree.map(zeros, params),
            jax.tree.map(zeros, params),
            jnp.zeros((), jnp.int32),
        )

    def fused_apply(self, params, grads, state):
        interpret = not _on_tpu()
        m_tree, v_tree, count = state
        t = (count + 1).astype(jnp.float32)
        c1 = 1.0 / (1.0 - self.b1**t)
        c2 = 1.0 / (1.0 - self.b2**t)
        scalars = jnp.stack([c1, c2]).reshape(1, 2)
        out = jax.tree.map(
            lambda p, g, m, v: _leaf_adam(
                p, g, m, v, scalars, self.learning_rate, self.b1,
                self.b2, self.eps, interpret,
            ),
            params,
            grads,
            m_tree,
            v_tree,
        )
        pick = lambda i: jax.tree.map(
            lambda trip: trip[i], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        return pick(0), (pick(1), pick(2), count + 1)
