"""Pallas TPU kernels — the framework's hand-written native tier.

The reference has no native components of its own (SURVEY §3.4); its compute
runs in the Keras backend. Here the equivalent tier is XLA-compiled JAX plus
these Pallas kernels for ops worth owning:

- ``fused_sgd``: the optimizer update applied in ONE pass over each
  parameter buffer (p' = p - lr*u and m' = mu*m + g computed together in
  VMEM), instead of the separate update/apply traffic of the generic
  optax path (reference: the worker optimizer step inside
  distkeras/workers.py -> Worker.train's ``train_on_batch``).

Kernels compile with Mosaic on TPU and fall back to interpreter mode on
CPU (tests run on the 8-device CPU mesh), chosen at trace time.

Layout: each parameter leaf is raveled and tiled to (rows, 128) f32 blocks
(lane width 128, sublane multiple 8 — see the Pallas TPU guide's tiling
table); leaves smaller than one tile use plain VPU-fused jnp math, where a
kernel launch would cost more than it saves.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
BLOCK_ROWS = 512  # (512, 128) f32 = 256 KiB per buffer — comfortably in VMEM
_MIN_KERNEL_SIZE = 8 * LANE  # below one f32 tile, jnp is cheaper


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _block_rows_for(n: int) -> int:
    """Per-leaf block height: the sublane-aligned row count, capped at
    BLOCK_ROWS — a leaf slightly over one tile pads to its own size, not to
    a full 512-row block (64x waste for small leaves otherwise)."""
    rows = pl.cdiv(n, LANE)
    return min(int(np.ceil(rows / 8)) * 8, BLOCK_ROWS)


def _pad_to_blocks(flat, block_rows):
    """(n,) -> (rows, LANE) with rows a multiple of ``block_rows``."""
    n = flat.shape[0]
    rows = pl.cdiv(n, LANE)
    rows_padded = int(np.ceil(rows / block_rows)) * block_rows
    flat = jnp.pad(flat, (0, rows_padded * LANE - n))
    return flat.reshape(rows_padded, LANE)


def _unpad(mat, shape, dtype):
    n = int(np.prod(shape)) if shape else 1
    return mat.reshape(-1)[:n].reshape(shape).astype(dtype)


# ----------------------------------------------------------------- kernels


def _sgd_kernel(lr, p_ref, g_ref, out_ref):
    out_ref[:] = p_ref[:] - lr * g_ref[:]


def _sgd_momentum_kernel(lr, mu, nesterov, p_ref, g_ref, m_ref, op_ref, om_ref):
    m_new = mu * m_ref[:] + g_ref[:]
    update = g_ref[:] + mu * m_new if nesterov else m_new
    op_ref[:] = p_ref[:] - lr * update
    om_ref[:] = m_new


def _block_specs(num, block_rows):
    return [
        pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
        for _ in range(num)
    ]


def _leaf_sgd(p, g, lr, interpret):
    shape, dtype = p.shape, p.dtype
    if p.size < _MIN_KERNEL_SIZE:
        return (p.astype(jnp.float32) - lr * g.astype(jnp.float32)).astype(dtype)
    br = _block_rows_for(p.size)
    pm = _pad_to_blocks(p.ravel().astype(jnp.float32), br)
    gm = _pad_to_blocks(g.ravel().astype(jnp.float32), br)
    out = pl.pallas_call(
        functools.partial(_sgd_kernel, lr),
        out_shape=jax.ShapeDtypeStruct(pm.shape, jnp.float32),
        grid=(pm.shape[0] // br,),
        in_specs=_block_specs(2, br),
        out_specs=_block_specs(1, br)[0],
        interpret=interpret,
    )(pm, gm)
    return _unpad(out, shape, dtype)


def _leaf_sgd_momentum(p, g, m, lr, mu, nesterov, interpret):
    shape, dtype = p.shape, p.dtype
    if p.size < _MIN_KERNEL_SIZE:
        p32, g32, m32 = (x.astype(jnp.float32) for x in (p, g, m))
        m_new = mu * m32 + g32
        update = g32 + mu * m_new if nesterov else m_new
        return (p32 - lr * update).astype(dtype), m_new
    br = _block_rows_for(p.size)
    pm = _pad_to_blocks(p.ravel().astype(jnp.float32), br)
    gm = _pad_to_blocks(g.ravel().astype(jnp.float32), br)
    mm = _pad_to_blocks(m.ravel().astype(jnp.float32), br)
    op, om = pl.pallas_call(
        functools.partial(_sgd_momentum_kernel, lr, mu, nesterov),
        out_shape=(
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
            jax.ShapeDtypeStruct(pm.shape, jnp.float32),
        ),
        grid=(pm.shape[0] // br,),
        in_specs=_block_specs(3, br),
        out_specs=tuple(_block_specs(2, br)),
        interpret=interpret,
    )(pm, gm, mm)
    return _unpad(op, shape, dtype), _unpad(om, shape, jnp.float32)


# ------------------------------------------------------------ optimizer API


class FusedSGD:
    """Fused-apply optimizer: one VMEM pass computes p' (and m') directly.

    Exposes the ``init``/``fused_apply`` protocol WorkerCore prefers over
    the two-step optax ``update``+``apply_updates`` when present.
    """

    def __init__(self, learning_rate=0.01, momentum=0.0, nesterov=False):
        if callable(learning_rate):
            raise TypeError(
                "pallas_sgd bakes the learning rate into the kernel and "
                "does not accept schedules; use optimizer 'sgd' with a "
                "schedule instead"
            )
        self.learning_rate = float(learning_rate)
        self.momentum = float(momentum)
        self.nesterov = bool(nesterov)

    def init(self, params):
        if self.momentum == 0.0:
            return ()
        return jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )

    def fused_apply(self, params, grads, state):
        interpret = not _on_tpu()
        if self.momentum == 0.0:
            new_params = jax.tree.map(
                lambda p, g: _leaf_sgd(p, g, self.learning_rate, interpret),
                params,
                grads,
            )
            return new_params, state
        out = jax.tree.map(
            lambda p, g, m: _leaf_sgd_momentum(
                p, g, m, self.learning_rate, self.momentum,
                self.nesterov, interpret,
            ),
            params,
            grads,
            state,
        )
        new_params = jax.tree.map(
            lambda pair: pair[0], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        new_state = jax.tree.map(
            lambda pair: pair[1], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        return new_params, new_state
