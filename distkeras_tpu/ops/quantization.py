"""Weight-only int8 quantization for the serving path.

No reference counterpart (SURVEY §3.4: the reference ships no native/perf
tier at all); this is a TPU-first lever. Decode and batched inference are
memory-bound — the v5e HBM streams every weight matrix once per token —
so halving/quartering weight bytes moves tokens/sec directly, while the
MXU still computes in the activation dtype (the int8 weights upcast in
registers; XLA fuses the cast into the matmul's operand read).

Scheme: symmetric per-output-channel scales. A quantized matrix is the
pytree `{"q": int8 (in, out), "s": f32 (out,)}` with
`w ≈ q * s[None, :]`. Because the scale is per OUTPUT column it commutes
through the matmul:

    x @ (q * s[None, :]) == (x @ q) * s[None, :]

so `qmatmul` never materializes the dequantized matrix — the int8 bytes
are what leaves HBM. Training on a quantized tree is unsupported (no
gradients through round()); quantize for serving, keep the f32 master
for training/checkpoints.
"""

from __future__ import annotations

import jax.numpy as jnp

#: weight-matrix key names eligible for quantization when walking a
#: params tree: Dense kernels and the attention projections. Biases, LN
#: gains, embeddings, and conv kernels stay f32 (they are a rounding
#: error of the bytes; embeddings are gathers, not matmuls).
DEFAULT_QUANT_KEYS = ("kernel", "wq", "wk", "wv", "wo")


def quantize_int8(w):
    """f32 (in, out) -> {"q": int8, "s": f32 (out,)}, symmetric per-column."""
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize_int8 expects a 2-D matrix; got {w.shape}")
    s = jnp.max(jnp.abs(w), axis=0) / 127.0
    s = jnp.where(s == 0, jnp.float32(1.0), s).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / s[None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def dequantize(w):
    """{"q","s"} -> f32 matrix (testing/debugging; serving never calls it)."""
    return w["q"].astype(jnp.float32) * w["s"][None, :]


def qshape(w):
    """Shape of a weight that may or may not be quantized."""
    return w["q"].shape if is_quantized(w) else w.shape


def qmatmul(x, w):
    """x @ w for plain or quantized w, in x.dtype, without materializing
    the dequantized matrix (the per-out-column scale commutes)."""
    if is_quantized(w):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w.astype(x.dtype)


def quantize_params(params, keys=DEFAULT_QUANT_KEYS):
    """Walk a params pytree; replace eligible 2-D float leaves (dict key in
    ``keys``) with their int8 form. Already-quantized entries pass through
    (idempotent). Returns a new tree; the input is not mutated."""
    if is_quantized(params):
        return params
    if isinstance(params, dict):
        out = {}
        for k, v in params.items():
            if (
                k in keys
                and hasattr(v, "ndim")
                and getattr(v, "ndim", 0) == 2
                and jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
            ):
                out[k] = quantize_int8(v)
            else:
                out[k] = quantize_params(v, keys)
        return out
    if isinstance(params, (list, tuple)):
        return type(params)(quantize_params(v, keys) for v in params)
    return params


def count_quantized(params) -> int:
    """Number of quantized matrices in a tree (tests/reporting)."""
    if is_quantized(params):
        return 1
    if isinstance(params, dict):
        return sum(count_quantized(v) for v in params.values())
    if isinstance(params, (list, tuple)):
        return sum(count_quantized(v) for v in params)
    return 0


def quantize_model(model, keys=DEFAULT_QUANT_KEYS):
    """Switch a built model's params to the int8 serving tree IN PLACE and
    return the model (chainable). Serve-only: trainers reject quantized
    trees (no gradients through round()); quantize a copy —
    ``quantize_model(m.copy())`` — if the original must keep training."""
    if getattr(model, "params", None) is None:
        raise ValueError("quantize_model needs a BUILT model (params set)")
    model.params = quantize_params(model.params, keys)
    return model
