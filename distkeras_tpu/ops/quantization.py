"""Weight-only int8 / int4 quantization for the serving path.

No reference counterpart (SURVEY §3.4: the reference ships no native/perf
tier at all); this is a TPU-first lever. Decode and batched inference are
memory-bound — the v5e HBM streams every weight matrix once per token —
so halving/quartering weight bytes moves tokens/sec directly, while the
MXU still computes in the activation dtype (the int8 weights upcast in
registers; XLA fuses the cast into the matmul's operand read).

Scheme: symmetric per-output-channel scales. An int8-quantized matrix is
the pytree `{"q": int8 (in, out), "s": f32 (out,)}` with
`w ≈ q * s[None, :]`. Because the scale is per OUTPUT column it commutes
through the matmul:

    x @ (q * s[None, :]) == (x @ q) * s[None, :]

so `qmatmul` never materializes the dequantized matrix — the int8 bytes
are what leaves HBM. Training on a quantized tree is unsupported (no
gradients through round()); quantize for serving, keep the f32 master
for training/checkpoints.

int4 (``bits=4``) halves the weight bytes again: values clip to [-7, 7]
and pack two-per-byte along the IN dimension (`Int4Weight`, a registered
pytree whose static aux carries the logical row count). This build's JAX
cannot materialize native ``jnp.int4`` arrays (convert_element_type on S4
recurses — re-checked 2026-08-01), so the packing is explicit int8 nibble
arithmetic; the unpack (two shifts + an interleave) fuses into the
matmul's operand read under XLA, and the packed bytes are what HBM
streams. Eighth-width weights cost accuracy headroom — the tests pin how
much on the zoo models; prefer int8 unless the bytes matter more.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: weight-matrix key names eligible for quantization when walking a
#: params tree: Dense kernels and the attention projections. Biases, LN
#: gains, embeddings, and conv kernels stay f32 (they are a rounding
#: error of the bytes; embeddings are gathers, not matmuls).
DEFAULT_QUANT_KEYS = ("kernel", "wq", "wk", "wv", "wo")


def quantize_int8(w):
    """f32 (in, out) -> {"q": int8, "s": f32 (out,)}, symmetric per-column."""
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize_int8 expects a 2-D matrix; got {w.shape}")
    s = jnp.max(jnp.abs(w), axis=0) / 127.0
    s = jnp.where(s == 0, jnp.float32(1.0), s).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / s[None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


@jax.tree_util.register_pytree_node_class
class Int4Weight:
    """Packed int4 weight: ``q4`` int8 (ceil(in/2), out) holding two
    4-bit values per byte (row 2i in the low nibble, row 2i+1 in the
    high), ``s`` f32 (out,) per-column scales. ``rows`` (the logical in
    dimension) rides the pytree's STATIC aux data, so it stays a Python
    int under jit and can shape the unpack."""

    def __init__(self, q4, s, rows):
        self.q4, self.s, self.rows = q4, s, rows

    def tree_flatten(self):
        return (self.q4, self.s), self.rows

    @classmethod
    def tree_unflatten(cls, rows, children):
        return cls(*children, rows=rows)


def quantize_int4(w):
    """f32 (in, out) -> Int4Weight, symmetric per-column, range [-7, 7].
    Odd in dims pad one zero row before packing (sliced off at unpack)."""
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize_int4 expects a 2-D matrix; got {w.shape}")
    rows, cols = w.shape
    s = jnp.max(jnp.abs(w), axis=0) / 7.0
    s = jnp.where(s == 0, jnp.float32(1.0), s).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / s[None, :]), -7, 7).astype(jnp.int8)
    if rows % 2:
        q = jnp.concatenate([q, jnp.zeros((1, cols), jnp.int8)], axis=0)
    packed = jnp.bitwise_or(
        jnp.left_shift(q[1::2], 4), jnp.bitwise_and(q[0::2], 0x0F)
    ).astype(jnp.int8)
    return Int4Weight(packed, s, rows)


def _unpack_int4(w):
    """Int4Weight -> int8 (rows, out). Low nibble sign-extends by the
    shift-up/arithmetic-shift-down trick; the high nibble's arithmetic
    right shift sign-extends directly."""
    p = w.q4
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    inter = jnp.stack([lo, hi], axis=1).reshape(-1, p.shape[1])
    return inter[: w.rows]


def is_quantized(w) -> bool:
    return isinstance(w, Int4Weight) or (
        isinstance(w, dict) and "q" in w and "s" in w
    )


def dequantize(w):
    """Quantized form -> f32 matrix (testing/debugging; serving never
    calls it)."""
    if isinstance(w, Int4Weight):
        return _unpack_int4(w).astype(jnp.float32) * w.s[None, :]
    return w["q"].astype(jnp.float32) * w["s"][None, :]


def qshape(w):
    """Logical shape of a weight that may or may not be quantized."""
    if isinstance(w, Int4Weight):
        return (w.rows, w.q4.shape[1])
    return w["q"].shape if is_quantized(w) else w.shape


def qmatmul(x, w):
    """x @ w for plain or quantized w, in x.dtype, without materializing
    the dequantized matrix (the per-out-column scale commutes)."""
    if isinstance(w, Int4Weight):
        return (x @ _unpack_int4(w).astype(x.dtype)) * w.s.astype(x.dtype)
    if is_quantized(w):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w.astype(x.dtype)


def quantize_params(params, keys=DEFAULT_QUANT_KEYS, bits=8):
    """Walk a params pytree; replace eligible 2-D float leaves (dict key in
    ``keys``) with their ``bits``-wide form (8 or 4). Already-quantized
    entries pass through unchanged — idempotent, and a tree quantized at
    one width is NOT re-quantized at another (round() already destroyed
    the master; re-quantize from the f32 original instead). Returns a new
    tree; the input is not mutated."""
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4; got {bits}")
    quant = quantize_int8 if bits == 8 else quantize_int4
    if is_quantized(params):
        return params
    if isinstance(params, dict):
        out = {}
        for k, v in params.items():
            if (
                k in keys
                and hasattr(v, "ndim")
                and getattr(v, "ndim", 0) == 2
                and jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
            ):
                out[k] = quant(v)
            else:
                out[k] = quantize_params(v, keys, bits)
        return out
    if isinstance(params, (list, tuple)):
        return type(params)(quantize_params(v, keys, bits) for v in params)
    return params


def count_quantized(params) -> int:
    """Number of quantized matrices in a tree (tests/reporting)."""
    if is_quantized(params):
        return 1
    if isinstance(params, dict):
        return sum(count_quantized(v) for v in params.values())
    if isinstance(params, (list, tuple)):
        return sum(count_quantized(v) for v in params)
    return 0


def quantize_model(model, keys=DEFAULT_QUANT_KEYS, bits=8):
    """Switch a built model's params to the int8/int4 serving tree IN
    PLACE and return the model (chainable). Serve-only: trainers reject
    quantized trees (no gradients through round()); quantize a copy —
    ``quantize_model(m.copy())`` — if the original must keep training."""
    if getattr(model, "params", None) is None:
        raise ValueError("quantize_model needs a BUILT model (params set)")
    model.params = quantize_params(model.params, keys, bits)
    return model
