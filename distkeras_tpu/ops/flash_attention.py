"""FlashAttention — fused attention Pallas TPU kernels, forward AND backward.

The reference has no attention anywhere (SURVEY §3.3/§5.7), so this module
has no reference counterpart; it is the single-chip performance tier of the
rebuild's long-context stack (VERDICT r2 task 6: the expected MFU
bottleneck is unfused attention). ``dense_attention`` materializes the
(B, H, T, T) score matrix in HBM and round-trips it through the softmax;
these kernels stream K/V blocks through VMEM with the same online softmax
the ring uses (`parallel.ring_attention._block_attention`), so scores never
leave the chip's on-chip memory and the matmuls stay MXU-shaped:

- forward: one program per (batch, head, q-block); ``fori_loop`` over K/V
  blocks accumulating (acc, running max, normalizer); emits the output
  block plus the logsumexp row statistics the backward pass needs.
- backward (FlashAttention-2 split): a dq kernel over q-blocks and a dk/dv
  kernel over k-blocks, each recomputing p = exp(s - lse) blockwise from
  the saved (q, k, v, lse, delta) instead of reading a stored score matrix.

Layouts: public API is the framework's (B, T, H, D) attention layout
(``MultiHeadSelfAttention.attention_fn`` contract); kernels run (B, H, T, D).
Compute is f32 inside the kernels regardless of input dtype (bf16 in, bf16
out — the MXU accumulates f32 anyway). Falls back to interpreter mode off
TPU (the 8-device CPU test mesh), and to the XLA-fused dense path when the
sequence does not tile (T not divisible by the block size).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# 512 measured on v5e (MFU_ATTRIB.jsonl, d512/L8/seq512 training step):
# bq=bk=128 -> 0.191, 256 -> 0.243, 512 -> 0.284 vs 0.255 XLA dense — the
# MXU wants 512-wide score matmuls; blocks clamp to T for shorter seqs
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
# full K+V per (batch, head) program must fit comfortably in ~16 MB VMEM
_VMEM_KV_BUDGET_BYTES = 8 * 1024 * 1024


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _causal_mask(s, iq, bq, j, bk):
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(k_pos <= q_pos, s, -jnp.inf)


# ------------------------------------------------------------------ forward


def _fwd_kernel(causal, scale, bk, q_ref, k_ref, v_ref, o_ref, lse_ref):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (bq, D)
    bq, d = q.shape
    nk = k_ref.shape[2] // bk
    if causal:
        # blocks entirely above the diagonal are fully masked — skip them
        # (half the matmul work at seq >> block); partial blocks still
        # mask elementwise inside the body
        nk = jnp.minimum(nk, (iq * bq + bq + bk - 1) // bk)

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        if causal:
            s = _causal_mask(s, iq, bq, j, bk)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        # fully-masked rows keep m == -inf; exp(-inf - -inf) is nan, so
        # guard the shift (same treatment as the ring's online softmax)
        shift = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - shift[:, None])
        corr = jnp.exp(jnp.where(jnp.isneginf(m), shift, m) - shift)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(
        0,
        nk,
        body,
        (
            jnp.zeros((bq, d), jnp.float32),
            jnp.full((bq,), -jnp.inf, jnp.float32),
            jnp.zeros((bq,), jnp.float32),
        ),
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(jnp.isneginf(m), -jnp.inf, m + jnp.log(l_safe))
    lse_ref[0, 0] = lse[:, None]


def _fwd(q, k, v, causal, bq, bk, interpret):
    """(B, H, T, D) -> (out, lse). lse is the scaled-score logsumexp.

    Row statistics (lse, and delta in the backward) travel as
    (B, H, T, 1): Mosaic requires a block's last two dims to be divisible
    by (8, 128) or equal to the array's — a (1, 1, bq) block on a
    (B, H, T) array has block[-2] == 1 != H and fails to lower on real
    TPU (the CPU interpreter never checks). With a trailing singleton the
    row block is (bq, 1): bq % 8 == 0 and 1 == array's last dim.
    """
    b, h, t, d = q.shape
    scale = 1.0 / (d**0.5)
    grid = (b, h, t // bq)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda i, j, iq: (i, j, iq, 0))
    kvspec = pl.BlockSpec((1, 1, t, d), lambda i, j, iq: (i, j, 0, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal, scale, bk),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[qspec, kvspec, kvspec],
        out_specs=(
            qspec,
            pl.BlockSpec((1, 1, bq, 1), lambda i, j, iq: (i, j, iq, 0)),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ----------------------------------------------------------------- backward


def _dq_kernel(
    causal, scale, bk,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # (bq, D)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0]  # (bq, 1) block -> (bq,)
    delta = delta_ref[0, 0][:, 0]
    bq, d = q.shape
    nk = k_ref.shape[2] // bk
    if causal:
        nk = jnp.minimum(nk, (iq * bq + bq + bk - 1) // bk)
    shift = jnp.where(jnp.isneginf(lse), 0.0, lse)

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            s = _causal_mask(s, iq, bq, j, bk)
        p = jnp.exp(s - shift[:, None])  # masked s=-inf -> p=0
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    causal, scale, bq,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
):
    ik = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)  # (bk, D)
    v = v_ref[0, 0].astype(jnp.float32)
    bk, d = k.shape
    nq = q_ref.shape[2] // bq
    # causal: q blocks strictly before this k block's start are fully
    # masked — start the loop at the diagonal
    q_start = (ik * bk) // bq if causal else 0

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, 0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        do_blk = do_ref[0, 0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.ds(i * bq, bq), :][:, 0]
        delta_blk = delta_ref[0, 0, pl.ds(i * bq, bq), :][:, 0]
        shift = jnp.where(jnp.isneginf(lse_blk), 0.0, lse_blk)
        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        if causal:
            s = _causal_mask(s, i, bq, ik, bk)
        p = jnp.exp(s - shift[:, None])
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        q_start, nq, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
    )
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd_blocks(t, d, bq, bk):
    """Clamp BACKWARD block sizes so each program's scoped VMEM fits.

    The backward kernels are hungrier than the forward: each program
    holds two full (t, d) streams (k+v for dq; q+do for dkv) plus ~4
    (bq, bk) f32 intermediates (s, p, dp, ds), and Mosaic double-buffers
    the streamed operands. On chip this bit at t=4096, d=64,
    bq=bk=512: "scoped allocation 16.64M > 16.00M limit" in the dkv
    kernel (v5e, 2026-08-01) — a failure the CPU interpreter can never
    see, since interpret mode doesn't model VMEM. The estimate below is
    deliberately coarse; its one calibration point is that it clamps
    the measured-failing (4096, 512, 512) case while leaving the
    measured-healthy (2048, 512, 512) one alone. Halving preserves
    divisibility for the power-of-two blocks ``effective_path`` picks;
    the guard skips candidates that stop tiling t (block == t short
    seqs never hit the budget anyway)."""

    def est(bq_, bk_):
        full = 2 * t * d * 4 * 2       # two full streams, double-buffered
        inter = 4 * bq_ * bk_ * 4      # s / p / dp / ds
        blocks = 6 * max(bq_, bk_) * d * 4  # block ins/outs + accumulators
        return full + inter + blocks

    while est(bq, bk) > _VMEM_KV_BUDGET_BYTES and max(bq, bk) > 128:
        big = "bq" if bq >= bk else "bk"
        cand = (bq if big == "bq" else bk) // 2
        if cand < 128 or t % cand != 0:
            break
        if big == "bq":
            bq = cand
        else:
            bk = cand
    return bq, bk


def _bwd(causal, bq, bk, interpret, residuals, dout):
    q, k, v, out, lse = residuals
    b, h, t, d = q.shape
    bq, bk = _bwd_blocks(t, d, bq, bk)
    scale = 1.0 / (d**0.5)
    # delta_i = sum_d do_i * o_i — rowwise, cheap in XLA, shared by both
    # backward kernels (the FlashAttention-2 trick that removes dp row sums);
    # keepdims: row stats travel as (B, H, T, 1), see _fwd's layout note
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True,
    )

    qspec = pl.BlockSpec((1, 1, bq, d), lambda i, j, g: (i, j, g, 0))
    full = pl.BlockSpec((1, 1, t, d), lambda i, j, g: (i, j, 0, 0))
    rowq = pl.BlockSpec((1, 1, bq, 1), lambda i, j, g: (i, j, g, 0))
    rowf = pl.BlockSpec((1, 1, t, 1), lambda i, j, g: (i, j, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal, scale, bk),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        grid=(b, h, t // bq),
        in_specs=[qspec, full, full, qspec, rowq, rowq],
        out_specs=qspec,
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    kspec = pl.BlockSpec((1, 1, bk, d), lambda i, j, g: (i, j, g, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal, scale, bq),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, t, d), v.dtype),
        ),
        grid=(b, h, t // bk),
        in_specs=[full, kspec, kspec, full, rowf, rowf],
        out_specs=(kspec, kspec),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


# -------------------------------------------------------------- custom VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, bq, bk, interpret):
    out, _ = _fwd(q, k, v, causal, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, causal, bq, bk, interpret):
    out, lse = _fwd(q, k, v, causal, bq, bk, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, bq, bk, interpret, residuals, dout):
    return _bwd(causal, bq, bk, interpret, residuals, dout)


_flash.defvjp(_flash_fwd, _flash_bwd)


def effective_path(t, head_dim, block_q=DEFAULT_BLOCK_Q,
                   block_k=DEFAULT_BLOCK_K):
    """(path, bq, bk) that ``flash_attention`` will actually run for
    sequence length ``t``: path is "flash", "blockwise" (K+V past the
    VMEM budget), or "dense" (T does not tile the clamped blocks); bq/bk
    are the clamped FORWARD block sizes. The single source of the
    dispatch decision — the dispatch below and the benchmark harnesses
    both read it, so an artifact can never claim a kernel that silently
    fell back. The backward re-clamps under its own VMEM model; read
    ``effective_bwd_blocks`` for what the bwd kernels actually run."""
    bq = min(block_q, t)
    bk = min(block_k, t)
    if 2 * t * head_dim * 4 > _VMEM_KV_BUDGET_BYTES:
        return "blockwise", bq, bk
    # T that does not tile the requested blocks first tries smaller blocks
    # (halving, floor 128 — the MXU tile) before surrendering to dense:
    # seq 640/768/1152 etc. should run the kernel at 128/256, not pay the
    # O(T^2) HBM score materialization (ADVICE r3 #1)
    bq = _largest_tiling_block(t, bq)
    bk = _largest_tiling_block(t, bk)
    if bq is None or bk is None:
        return "dense", min(block_q, t), min(block_k, t)
    return "flash", bq, bk


def effective_bwd_blocks(t, head_dim, block_q=DEFAULT_BLOCK_Q,
                         block_k=DEFAULT_BLOCK_K):
    """(bq, bk) the BACKWARD kernels will actually run for sequence
    length ``t`` on the flash path: ``effective_path``'s forward blocks
    re-clamped by the backward VMEM model (``_bwd_blocks`` — the same
    function ``_bwd`` itself calls, so harness artifacts and the
    dispatch agree by construction). None when the path isn't flash
    (no backward kernel runs)."""
    path, bq, bk = effective_path(t, head_dim, block_q, block_k)
    if path != "flash":
        return None
    return _bwd_blocks(t, head_dim, bq, bk)


def _largest_tiling_block(t, block):
    """Largest candidate in {block, block/2, ..., 128} ∪ {t} that divides
    ``t``, or None. Mosaic wants q-blocks a multiple of 8; halving from a
    power-of-two default keeps that invariant."""
    if t % block == 0:  # covers the clamped block == t short-seq case
        return block
    cand = block // 2
    while cand >= 128:
        if t % cand == 0:
            return cand
        cand //= 2
    return None


def flash_attention(
    q, k, v, causal=False,
    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
):
    """Fused attention in the framework layout: (batch, seq, heads, head_dim).

    Numerically matches ``parallel.ring_attention.dense_attention`` (same
    online-softmax math) for values and gradients; self-attention only.
    Sequences that do not tile (T % block != 0) first retry smaller blocks
    (halving, floor 128 — see ``effective_path``), and only fall back to
    the XLA dense path when no block tiles; never pads — correctness must
    not depend on the fast path.
    """
    from distkeras_tpu.parallel.ring_attention import (
        blockwise_attention,
        dense_attention,
    )

    if k.shape[1] != q.shape[1] or v.shape[1] != q.shape[1]:
        raise ValueError(
            "flash_attention is self-attention only: expected k/v seq "
            f"length {q.shape[1]} (q's), got k={k.shape[1]}, v={v.shape[1]}"
        )
    t, d = q.shape[1], q.shape[3]
    path, bq, bk = effective_path(t, d, block_q, block_k)
    # each program holds the full K+V (f32) in VMEM; past ~8 MB of the
    # ~16 MB/core the Mosaic lowering fails, so long contexts take the
    # lax.scan blockwise path (same online softmax, HBM-streamed); T that
    # does not tile the blocks takes the XLA dense path rather than padding
    if path == "blockwise":
        return blockwise_attention(q, k, v, causal=causal)
    if path == "dense":
        return dense_attention(q, k, v, causal=causal)
    # (B, T, H, D) -> (B, H, T, D) for the kernels, and back
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out = _flash(qt, kt, vt, causal, bq, bk, not _on_tpu())
    return jnp.swapaxes(out, 1, 2)


def attach_flash_attention(model, block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K) -> int:
    """Point every MultiHeadSelfAttention at the fused kernel (single-chip
    fast path). Returns how many were attached. Process-local, like the
    ring/blockwise hooks — not serialized."""
    from distkeras_tpu.parallel.ring_attention import attach_attention_fn

    return attach_attention_fn(
        model, functools.partial(flash_attention, block_q=block_q,
                                 block_k=block_k)
    )
