"""Worker-optimizer resolution (Keras-style names -> optax transforms).

The reference passes a ``worker_optimizer`` string/object through to Keras
``model.compile`` (reference: distkeras/trainers.py -> Trainer.__init__,
distkeras/workers.py -> Worker.prepare_model). Here the same kwarg resolves
to an ``optax.GradientTransformation``; callables and ready-made optax
transforms pass through untouched.
"""

from __future__ import annotations

import optax


def _sgd(learning_rate=0.01, momentum=0.0, nesterov=False):
    if momentum:
        return optax.sgd(learning_rate, momentum=momentum, nesterov=nesterov)
    return optax.sgd(learning_rate)


def _pallas_sgd(learning_rate=0.01, momentum=0.0, nesterov=False):
    """Fused single-pass SGD update as a Pallas TPU kernel (see
    ops/pallas_kernels.py); numerically identical to "sgd"."""
    from distkeras_tpu.ops.pallas_kernels import FusedSGD

    return FusedSGD(learning_rate, momentum=momentum, nesterov=nesterov)


def _pallas_adam(learning_rate=1e-3, **kwargs):
    """Fused single-pass Adam update as a Pallas TPU kernel (see
    ops/pallas_kernels.py); numerically identical to "adam"."""
    from distkeras_tpu.ops.pallas_kernels import FusedAdam

    return FusedAdam(learning_rate, **kwargs)


_OPTIMIZERS = {
    "sgd": _sgd,
    "pallas_sgd": _pallas_sgd,
    "pallas_adam": _pallas_adam,
    "adam": optax.adam,
    "adamw": optax.adamw,
    "adagrad": optax.adagrad,
    "adadelta": optax.adadelta,
    "rmsprop": optax.rmsprop,
    "nadam": optax.nadam,
    "lamb": optax.lamb,
}

_DEFAULT_LR = {"sgd": 0.01, "pallas_sgd": 0.01, "pallas_adam": 1e-3,
               "adam": 1e-3, "adamw": 1e-3,
               "adagrad": 1e-2, "adadelta": 1e-3, "rmsprop": 1e-3,
               "nadam": 1e-3, "lamb": 1e-3}


_SCHEDULES = {
    "constant": optax.constant_schedule,
    "exponential_decay": optax.exponential_decay,
    "cosine_decay": optax.cosine_decay_schedule,
    "linear": optax.linear_schedule,
    "warmup_cosine": optax.warmup_cosine_decay_schedule,
}


def get_schedule(name, **kwargs):
    """A named optax learning-rate schedule (pass the result as a trainer's
    ``learning_rate``; optax optimizers accept schedules wherever they
    accept a float). No reference counterpart (the reference's Keras-era
    optimizers carry a fixed lr); schedules are standard TPU-era practice
    (warmup tames bf16 early training).

        get_schedule("warmup_cosine", init_value=0.0, peak_value=1e-3,
                     warmup_steps=100, decay_steps=2000)
    """
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _SCHEDULES:
        raise ValueError(
            f"unknown schedule {name!r}; available: {sorted(_SCHEDULES)}"
        )
    return _SCHEDULES[key](**kwargs)


def effective_learning_rate(name, learning_rate=None) -> float:
    """The lr the resolved optimizer will actually run with.

    Algorithms whose PS/elastic rules scale by the learning rate (AEASGD's
    alpha = rho*lr, ADAG's commit -lr/W) must use the same value the local
    optimizer steps with. A schedule contributes its step-0 value (the
    elastic/commit scaling stays constant over training — document in the
    trainer if you need otherwise). For callables/ready-made transforms the
    lr cannot be introspected; fall back to 0.01 (callers should pass
    learning_rate explicitly in that case).
    """
    if learning_rate is not None:
        if callable(learning_rate):  # optax schedule
            return float(learning_rate(0))
        return float(learning_rate)
    if isinstance(name, str) and name.lower() in _DEFAULT_LR:
        return _DEFAULT_LR[name.lower()]
    return 0.01


def get_optimizer(name, learning_rate=None, **kwargs):
    """Resolve a name/transform to an optax GradientTransformation."""
    if isinstance(name, optax.GradientTransformation):
        return name
    if callable(name):
        return name(learning_rate, **kwargs) if learning_rate is not None else name(**kwargs)
    key = str(name).lower()
    if key not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {name!r}; known: {sorted(_OPTIMIZERS)}")
    lr = learning_rate if learning_rate is not None else _DEFAULT_LR[key]
    return _OPTIMIZERS[key](lr, **kwargs)
