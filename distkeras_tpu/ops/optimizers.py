"""Worker-optimizer resolution (Keras-style names -> optax transforms).

The reference passes a ``worker_optimizer`` string/object through to Keras
``model.compile`` (reference: distkeras/trainers.py -> Trainer.__init__,
distkeras/workers.py -> Worker.prepare_model). Here the same kwarg resolves
to an ``optax.GradientTransformation``; callables and ready-made optax
transforms pass through untouched.
"""

from __future__ import annotations

import optax


def _sgd(learning_rate=0.01, momentum=0.0, nesterov=False):
    if momentum:
        return optax.sgd(learning_rate, momentum=momentum, nesterov=nesterov)
    return optax.sgd(learning_rate)


def _pallas_sgd(learning_rate=0.01, momentum=0.0, nesterov=False):
    """Fused single-pass SGD update as a Pallas TPU kernel (see
    ops/pallas_kernels.py); numerically identical to "sgd"."""
    from distkeras_tpu.ops.pallas_kernels import FusedSGD

    return FusedSGD(learning_rate, momentum=momentum, nesterov=nesterov)


_OPTIMIZERS = {
    "sgd": _sgd,
    "pallas_sgd": _pallas_sgd,
    "adam": optax.adam,
    "adamw": optax.adamw,
    "adagrad": optax.adagrad,
    "adadelta": optax.adadelta,
    "rmsprop": optax.rmsprop,
    "nadam": optax.nadam,
    "lamb": optax.lamb,
}

_DEFAULT_LR = {"sgd": 0.01, "pallas_sgd": 0.01, "adam": 1e-3, "adamw": 1e-3,
               "adagrad": 1e-2, "adadelta": 1e-3, "rmsprop": 1e-3,
               "nadam": 1e-3, "lamb": 1e-3}


def effective_learning_rate(name, learning_rate=None) -> float:
    """The lr the resolved optimizer will actually run with.

    Algorithms whose PS/elastic rules scale by the learning rate (AEASGD's
    alpha = rho*lr, ADAG's commit -lr/W) must use the same value the local
    optimizer steps with. For callables/ready-made transforms the lr cannot
    be introspected; fall back to 0.01 (callers should pass learning_rate
    explicitly in that case).
    """
    if learning_rate is not None:
        return float(learning_rate)
    if isinstance(name, str) and name.lower() in _DEFAULT_LR:
        return _DEFAULT_LR[name.lower()]
    return 0.01


def get_optimizer(name, learning_rate=None, **kwargs):
    """Resolve a name/transform to an optax GradientTransformation."""
    if isinstance(name, optax.GradientTransformation):
        return name
    if callable(name):
        return name(learning_rate, **kwargs) if learning_rate is not None else name(**kwargs)
    key = str(name).lower()
    if key not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {name!r}; known: {sorted(_OPTIMIZERS)}")
    lr = learning_rate if learning_rate is not None else _DEFAULT_LR[key]
    return _OPTIMIZERS[key](lr, **kwargs)
