"""Metrics (reference: distkeras/evaluators.py computes accuracy driver-side)."""

from __future__ import annotations

import jax.numpy as jnp


def accuracy(y_pred, y_true):
    """Fraction of argmax matches. y_true may be one-hot or integer ids."""
    pred_ids = jnp.argmax(y_pred, axis=-1)
    true_ids = y_true if y_true.ndim == y_pred.ndim - 1 else jnp.argmax(y_true, axis=-1)
    return jnp.mean((pred_ids == true_ids).astype(jnp.float32))


def next_token_accuracy(y_pred, y_true):
    """Causal-LM companion to ``losses.next_token_crossentropy``: position
    t's logits (B, T, V) are scored against token t+1 (B, T)."""
    pred_ids = jnp.argmax(y_pred[:, :-1], axis=-1)
    return jnp.mean((pred_ids == y_true[:, 1:].astype(pred_ids.dtype))
                    .astype(jnp.float32))


_METRICS = {
    "accuracy": accuracy,
    "acc": accuracy,
    "next_token_accuracy": next_token_accuracy,
}


def get_metric(name):
    if callable(name):
        return name
    if name not in _METRICS:
        raise ValueError(f"unknown metric {name!r}")
    return _METRICS[name]
