"""Loss functions, resolvable by Keras-style string names.

The reference passes loss names straight through to Keras ``model.compile``
(reference: distkeras/workers.py -> Worker.prepare_model compiles with the
trainer's ``loss`` kwarg). Same contract here: trainers accept either a name
or a callable ``loss(y_pred, y_true) -> scalar``.

Cross-entropy takes softmax *probabilities* (the zoo models end in softmax,
like the reference's Keras models) and is computed via clipped log for
numerical safety; models emitting logits can use the ``*_from_logits`` forms.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import nn

_EPS = 1e-7


def categorical_crossentropy(y_pred, y_true):
    """Mean CE; y_pred = probabilities (B, C); y_true = one-hot (B, C)."""
    p = jnp.clip(y_pred, _EPS, 1.0)
    return -jnp.mean(jnp.sum(y_true * jnp.log(p), axis=-1))


def categorical_crossentropy_from_logits(y_pred, y_true):
    return -jnp.mean(jnp.sum(y_true * nn.log_softmax(y_pred, axis=-1), axis=-1))


def sparse_categorical_crossentropy(y_pred, y_true):
    """y_true = integer class ids (B,)."""
    p = jnp.clip(y_pred, _EPS, 1.0)
    ll = jnp.take_along_axis(
        jnp.log(p), y_true.astype(jnp.int32)[:, None], axis=-1
    )
    return -jnp.mean(ll)


def next_token_crossentropy(y_pred, y_true):
    """Causal-LM loss: y_pred = logits (B, T, V); y_true = token ids (B, T).

    Position t's logits predict token t+1 (the standard shift); the last
    position has no target and is dropped. Mean over B*(T-1) predictions.
    No reference counterpart (no sequence models upstream — SURVEY §5.7);
    pairs with ``zoo.transformer_lm``'s causal blocks. Requires T >= 2:
    with a single position there is no (input, next-token) pair and the
    mean would silently reduce an empty slice to NaN (ADVICE r3 #4)."""
    if y_pred.shape[1] < 2:
        raise ValueError(
            "next_token_crossentropy needs seq_len >= 2 (got "
            f"{y_pred.shape[1]}): the shifted loss has no targets at T=1"
        )
    logp = nn.log_softmax(y_pred[:, :-1].astype(jnp.float32), axis=-1)
    targets = y_true[:, 1:].astype(jnp.int32)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def binary_crossentropy(y_pred, y_true):
    p = jnp.clip(y_pred, _EPS, 1.0 - _EPS)
    return -jnp.mean(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))


def _check_regression_shapes(y_pred, y_true):
    """(B, 1) predictions against a (B,) target silently broadcast to a
    (B, B) residual matrix — a wrong loss with no error (the classic
    Keras regression footgun). Require identical shapes."""
    if y_pred.shape != y_true.shape:
        raise ValueError(
            f"regression loss needs matching shapes; got y_pred "
            f"{y_pred.shape} vs y_true {y_true.shape} — reshape the "
            "target to the prediction's shape (loaders.diabetes ships "
            "its target as (n, 1))"
        )


def mse(y_pred, y_true):
    _check_regression_shapes(y_pred, y_true)
    return jnp.mean((y_pred - y_true) ** 2)


def mae(y_pred, y_true):
    _check_regression_shapes(y_pred, y_true)
    return jnp.mean(jnp.abs(y_pred - y_true))


_LOSSES = {
    "categorical_crossentropy": categorical_crossentropy,
    "categorical_crossentropy_from_logits": categorical_crossentropy_from_logits,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "next_token_crossentropy": next_token_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mse": mse,
    "mean_squared_error": mse,
    "mae": mae,
    "mean_absolute_error": mae,
}


def get_loss(name):
    if callable(name):
        return name
    if name not in _LOSSES:
        raise ValueError(f"unknown loss {name!r}; known: {sorted(_LOSSES)}")
    return _LOSSES[name]
