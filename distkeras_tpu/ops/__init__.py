"""Compute ops: losses, metrics, optimizer resolution, fused/Pallas kernels."""

from distkeras_tpu.ops.losses import get_loss, categorical_crossentropy, mse
from distkeras_tpu.ops.metrics import accuracy
from distkeras_tpu.ops.optimizers import get_optimizer, get_schedule
from distkeras_tpu.ops.quantization import (
    Int4Weight,
    dequantize,
    qmatmul,
    quantize_int4,
    quantize_int8,
    quantize_model,
    quantize_params,
)
