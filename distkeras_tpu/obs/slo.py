"""Declarative SLOs evaluated from the typed-metrics registries.

PR 7 made every subsystem scrapeable; what was still missing is the
judgment layer: the soak bar ("0 hung / 0 untyped") is asserted by
harnesses, but in production the metrics are numbers a human must
eyeball. An :class:`SloSpec` turns one registry series into an
automatic verdict — p99 latency under a bound, error rate under a
ceiling, speculative acceptance over a floor, PS straggler ratio
under a cap — and :func:`evaluate_slos` grades a whole spec list
against a ``metrics_snapshot()`` sample list (pure function: the
tests drive it without an engine).

Verdicts are three-valued, per the usual burn-rate discipline:

- ``ok``      — every spec within its target
- ``warn``    — some spec past its ``warn`` threshold but not its
  breach threshold (the page-later tier)
- ``breach``  — some spec past its ``threshold`` (the page-now tier)

:class:`SloEvaluator` is the component-side wrapper: cadence-guarded
evaluation (``maybe_evaluate`` at most once per ``interval``, so a
health poll costs a dict read between evaluations), a breach counter
in the owning registry (``<prefix>_slo_breaches``), and a
``slo.breach`` / ``slo.warn`` event in the component's flight
recorder — so an SLO violation is part of the post-mortem timeline,
not a separate dashboard's memory. The engine rides verdicts on the
``health`` verb (``slo``/``slo_violations``), and the fleet health
sweep can optionally EJECT a replica on sustained breach
(``FleetRouter(eject_on_slo_breach=N)``).

Spec semantics: ``agg`` picks how the named series reduces to one
number — ``"value"`` (counter/gauge sample value), ``"p50"``/
``"p99"`` (histogram bucket-resolution quantile), ``"mean"``
(histogram sum/count), ``"rate"`` (this series' value divided by
``per``'s value — error rates, acceptance rates). ``bound`` is the
direction: ``"max"`` means values ABOVE the threshold violate,
``"min"`` means values below do. ``min_count`` refuses to judge a
histogram/rate with fewer observations (a single slow request must
not page anyone).
"""

from __future__ import annotations

import time

OK, WARN, BREACH = "ok", "warn", "breach"


class SloSpec:
    """One service-level objective over one registry series.

    ``labels``: optional label constraints — the spec then grades the
    first sample of ``series`` whose labels CONTAIN them (e.g.
    ``{"tenant": "interactive"}`` picks that tenant's labeled latency
    histogram). Empty/None keeps the historical behavior: the first
    sample under the name (a component's own book)."""

    __slots__ = ("name", "series", "threshold", "warn", "agg", "bound",
                 "per", "min_count", "labels")

    def __init__(self, name: str, series: str, threshold: float,
                 warn: float | None = None, agg: str = "value",
                 bound: str = "max", per: str | None = None,
                 min_count: int = 1, labels: dict | None = None):
        if agg not in ("value", "p50", "p99", "mean", "rate"):
            raise ValueError(f"unknown agg {agg!r}")
        if bound not in ("max", "min"):
            raise ValueError(f"bound must be 'max' or 'min'; got {bound!r}")
        if agg == "rate" and per is None:
            raise ValueError("agg='rate' needs per= (the denominator series)")
        self.name = name
        self.series = series
        self.threshold = float(threshold)
        self.warn = None if warn is None else float(warn)
        self.agg = agg
        self.bound = bound
        self.per = per
        self.min_count = int(min_count)
        self.labels = dict(labels or {})

    def describe(self) -> dict:
        return {
            "name": self.name, "series": self.series,
            "threshold": self.threshold, "warn": self.warn,
            "agg": self.agg, "bound": self.bound, "per": self.per,
            "labels": dict(self.labels),
        }


def _hist_quantile(sample: dict, q: float):
    """Bucket-resolution quantile out of a histogram SAMPLE (the same
    estimate ``Histogram.quantile`` computes live)."""
    count = sample.get("count", 0)
    if not count:
        return None
    target = max(1, int(q * count))
    last = None
    for le, cum in sample["buckets"]:
        if le != "+Inf":
            last = float(le)
        if cum >= target:
            return last  # the +Inf bucket reports the top finite bound
    return last


def _pick_sample(by_name: dict, series: str, labels: dict):
    """The sample a spec grades: first sample under the name whose
    labels contain ``labels`` (empty labels = the first sample, the
    historical component-own-book behavior)."""
    for s in by_name.get(series, ()):
        have = s.get("labels") or {}
        if all(have.get(k) == v for k, v in labels.items()):
            return s
    return None


def _reduce(spec: SloSpec, by_name: dict):
    """Reduce ``spec``'s series to ``(value, count)`` from the sample
    index; value None = not judgeable (missing series, empty
    histogram, zero denominator)."""
    s = _pick_sample(by_name, spec.series, spec.labels)
    if s is None:
        return None, 0
    if spec.agg == "value":
        v = s.get("value")
        return (None if v is None else float(v)), 1
    if spec.agg in ("p50", "p99"):
        q = 0.5 if spec.agg == "p50" else 0.99
        return _hist_quantile(s, q), int(s.get("count", 0))
    if spec.agg == "mean":
        count = int(s.get("count", 0))
        if not count:
            return None, 0
        return float(s["sum"]) / count, count
    # rate: numerator value / denominator value
    den = _pick_sample(by_name, spec.per, spec.labels)
    num_v = s.get("value")
    den_v = None if den is None else den.get("value")
    if num_v is None or not den_v:
        return None, 0
    return float(num_v) / float(den_v), int(den_v)


def evaluate_slos(samples, specs) -> dict:
    """Grade ``specs`` against a ``metrics_snapshot()`` sample list.
    Returns ``{"slo": ok|warn|breach, "violations": [...], "specs":
    [...]}`` — ``violations`` names the violating series with the
    measured value and the crossed threshold (what the ``health``
    verb ships), ``specs`` is the full per-spec detail."""
    by_name: dict = {}
    for s in samples:
        # every sample under the name, in arrival order: unlabeled
        # specs read the first (own book — the historical behavior),
        # labeled specs find their (e.g. per-tenant) twin
        by_name.setdefault(s["name"], []).append(s)
    detail = []
    worst = OK
    violations = []
    for spec in specs:
        value, count = _reduce(spec, by_name)
        verdict = OK
        if value is None or count < spec.min_count:
            verdict = OK  # not judgeable ≠ violated
        else:
            over = (
                value > spec.threshold
                if spec.bound == "max"
                else value < spec.threshold
            )
            warned = spec.warn is not None and (
                value > spec.warn
                if spec.bound == "max"
                else value < spec.warn
            )
            verdict = BREACH if over else (WARN if warned else OK)
        row = {
            "name": spec.name,
            "series": spec.series,
            "agg": spec.agg,
            "value": value,
            "threshold": spec.threshold,
            "warn": spec.warn,
            "bound": spec.bound,
            "verdict": verdict,
        }
        if spec.labels:
            row["labels"] = dict(spec.labels)  # names WHOSE series
        detail.append(row)
        if verdict != OK:
            violations.append(
                {k: row[k] for k in
                 ("name", "series", "value", "threshold", "verdict")}
            )
        if verdict == BREACH or (verdict == WARN and worst == OK):
            worst = verdict
    return {"slo": worst, "violations": violations, "specs": detail}


class SloEvaluator:
    """Component-side SLO watchdog: cadence-guarded evaluation over a
    snapshot callable, verdicts cached between evaluations, breaches
    counted in the registry and recorded in the flight recorder."""

    def __init__(self, specs, snapshot_fn, interval: float = 5.0,
                 registry=None, recorder=None, prefix: str = "serving"):
        self.specs = list(specs)
        self._snapshot_fn = snapshot_fn
        self.interval = float(interval)
        self._recorder = recorder
        self._last_eval = 0.0
        self._verdict = {"slo": OK, "violations": [], "specs": []}
        self._breach_counter = None
        if registry is not None:
            self._breach_counter = registry.counter(
                f"{prefix}_slo_breaches"
            )
            registry.gauge(
                f"{prefix}_slo_status",
                fn=lambda: {OK: 0, WARN: 1, BREACH: 2}[
                    self._verdict["slo"]
                ],
            )

    @property
    def verdict(self) -> dict:
        return self._verdict

    def evaluate(self) -> dict:
        """Forced evaluation (post-mortem dumps call this so the
        bundle carries a verdict as of the failure, not a stale one)."""
        prev = self._verdict["slo"]
        v = evaluate_slos(self._snapshot_fn(), self.specs)
        self._verdict = v
        self._last_eval = time.monotonic()
        if v["slo"] == BREACH:
            if self._breach_counter is not None:
                self._breach_counter.inc()
            if self._recorder is not None and prev != BREACH:
                # record the TRANSITION into breach (a sustained breach
                # is one incident, not one ring entry per health poll)
                self._recorder.record(
                    "slo.breach", violations=v["violations"]
                )
        elif v["slo"] == WARN and prev == OK and self._recorder is not None:
            self._recorder.record("slo.warn", violations=v["violations"])
        return v

    def maybe_evaluate(self) -> dict:
        """Evaluate at most once per ``interval``; between evaluations
        the cached verdict is returned (a router polling health every
        250 ms costs a float compare, not a registry walk)."""
        if time.monotonic() - self._last_eval >= self.interval:
            return self.evaluate()
        return self._verdict


def default_serving_slos(latency_p99_s=None, ttft_p99_s=None,
                         error_rate=None, acceptance_rate=None,
                         min_count=20,
                         tenant_latency_p99_s=None,
                         overlap_efficiency_min=None) -> list[SloSpec]:
    """The serving-tier spec set, opt-in per knob (None = not
    enforced): end-to-end p99 latency, TTFT p99, typed-internal error
    rate (internal errors / submitted — the denominator includes
    rejected and in-flight requests, so set the ceiling against total
    offered load), the speculative acceptance floor (mean tokens per
    verify window), and the overlap-efficiency floor (device-wall /
    iteration-wall from the zero-bubble decode ledger).

    ``tenant_latency_p99_s``: tenant name -> p99 bound (seconds) —
    one spec per tenant over that tenant's LABELED latency histogram
    (``serving_request_total_seconds{tenant=...}``), so a QoS
    violation is attributable to the tenant whose SLO it broke, not
    smeared into the fleet-wide tail."""
    specs = []
    for t, bound in (tenant_latency_p99_s or {}).items():
        specs.append(SloSpec(
            f"latency_p99[{t}]", "serving_request_total_seconds",
            bound, agg="p99", min_count=min_count,
            labels={"tenant": str(t)},
        ))
    if latency_p99_s is not None:
        specs.append(SloSpec(
            "latency_p99", "serving_request_total_seconds",
            latency_p99_s, agg="p99", min_count=min_count,
        ))
    if ttft_p99_s is not None:
        specs.append(SloSpec(
            "ttft_p99", "serving_request_ttft_seconds", ttft_p99_s,
            agg="p99", min_count=min_count,
        ))
    if error_rate is not None:
        specs.append(SloSpec(
            "error_rate", "serving_scheduler_internal_errors",
            error_rate, agg="rate", per="serving_scheduler_submitted",
            min_count=min_count,
        ))
    if acceptance_rate is not None:
        specs.append(SloSpec(
            "acceptance_rate", "serving_scheduler_spec_tokens",
            acceptance_rate, agg="rate",
            per="serving_scheduler_spec_windows", bound="min",
            min_count=min_count,
        ))
    if overlap_efficiency_min is not None:
        # the zero-bubble floor: cumulative device-wall / iteration-
        # wall from the overlap ledger (gauge is None before the
        # first completed iteration — not judgeable, not a breach)
        specs.append(SloSpec(
            "overlap_efficiency", "serving_overlap_efficiency",
            overlap_efficiency_min, agg="value", bound="min",
        ))
    return specs


def default_training_slos(straggler_ratio=None, commit_interval_p99_s=None,
                          gate_refusal_rate=None, min_count=8) -> list[SloSpec]:
    """The training-tier (PS) spec set: the straggler ratio
    (max/median per-worker commit interval), the fleet-wide commit
    interval p99, and the durability-gate refusal rate (refused /
    commits) — the commit-lag bounds of the DOWNPOUR/AEASGD paths."""
    specs = []
    if straggler_ratio is not None:
        specs.append(SloSpec(
            "straggler", "training_ps_straggler", straggler_ratio,
            agg="value",
        ))
    if commit_interval_p99_s is not None:
        specs.append(SloSpec(
            "commit_interval_p99", "training_ps_commit_interval_seconds",
            commit_interval_p99_s, agg="p99", min_count=min_count,
        ))
    if gate_refusal_rate is not None:
        specs.append(SloSpec(
            "gate_refusals", "training_ps_commits_refused_no_replica",
            gate_refusal_rate, agg="rate", per="training_ps_commits",
            min_count=min_count,
        ))
    return specs
