"""Runtime ledger of XLA program mints — the compile black box.

XLA compiles are the serving tier's least visible stall class: a mint
on the serving path blocks the scheduler thread for tens to hundreds
of milliseconds (PERF.md r14 committed a 0.17x bench ratio to mid-pass
compiles before the keying was fixed structurally; r16 found a ~240 ms
compile stall inside an interactive p99), yet until this module the
only trace was the watchdog's grace extension. The ledger instruments
the one chokepoint every serving program passes through
(``DecodeStepper._jit``) so EVERY mint records:

- ``key`` — the program family and bucket (``"admit[16]"``,
  ``"paged_step[4,masked]"``), stamped at the ``_jit`` call site;
- ``seconds`` — the wall time the calling thread lost to the mint
  (trace + compile + first dispatch: the stall a request actually
  experienced, not the compiler's self-reported time);
- ``trigger`` — ``"warmup"`` (inside ``DecodeStepper.warmup()``, the
  off-path place compiles belong) or ``"serving"`` (the live path);
- ``inflight`` — how many requests were queued/active at mint time
  (the blast radius);
- ``rewarm`` — True when this (key, shape-signature) was already
  minted by an earlier stepper generation: a supervisor restart
  recompiling a known-hot program is expected, not a storm.

Detection rides jax's backend-compile monitoring event (fired
synchronously, on the calling thread, once per REAL compile — an
executable-cache-size heuristic was observed to lag the compile by
several calls and then blame an innocent later one), so a silent
RETRACE of an existing program — the layout-drift class
``out_shardings`` pinning exists to prevent — is caught exactly like
a fresh bucket; when the monitoring API is absent the wrapper falls
back to first-call-per-program detection.

**Compile-storm detection**: once :meth:`CompileLedger.mark_warmed`
has been called (a harness's explicit "the warm set is complete"
boundary, after ``warmup()`` + the ``warm_*_buckets`` warms its
traffic needs), any serving-path mint of a program
signature never seen before is a STORM — it records an
``xla.compile.storm`` flight-recorder event and ticks the
``serving_compile_storms`` gauge. Both soaks assert zero storms, and
``tools/check_bench.py`` holds the committed invariant that timed
bench passes contain no mints at all — the twice-repeated bench
post-mortem turned into a standing gate.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class CompileLedger:
    """Engine-owned mint ledger, shared across supervisor-rebuilt
    stepper generations (restart recompiles are attributed, and the
    counters never reset mid-window underneath ``MetricsHistory``).

    ``registry``: registers ``<prefix>_compiles`` /
    ``<prefix>_compile_seconds`` counters and the
    ``<prefix>_compile_storms`` / ``<prefix>_compile_warmed`` gauges.
    ``recorder``: every mint lands as an ``xla.compile`` event (storms
    additionally as ``xla.compile.storm``). ``inflight_fn``: cheap
    callable for the requests-in-flight stamp (the engine wires the
    scheduler's occupancy)."""

    def __init__(self, registry=None, recorder=None,
                 prefix: str = "serving", capacity: int = 256,
                 inflight_fn=None):
        self._records: deque = deque(maxlen=int(capacity))
        self._seen: set = set()
        self._lock = threading.Lock()
        self.recorder = recorder
        self.inflight_fn = inflight_fn
        self.warmed = False
        self.total = 0
        self.warmup_mints = 0
        self.serving_mints = 0
        self.rewarms = 0
        self.storms = 0
        self.seconds = 0.0
        self._compiles_counter = None
        self._seconds_counter = None
        if registry is not None:
            # counters (not gauges): mints only accumulate, and the
            # history layer computes windowed compile RATES from them
            self._compiles_counter = registry.counter(
                f"{prefix}_compiles",
                help="XLA programs minted (compiled) at runtime",
            )
            self._seconds_counter = registry.counter(
                f"{prefix}_compile_seconds",
                help="wall seconds serving threads lost to XLA mints",
            )
            registry.gauge(
                f"{prefix}_compile_storms",
                fn=lambda: self.storms,
                help="post-warmup serving-path mints of never-seen "
                     "programs",
            )
            registry.gauge(
                f"{prefix}_compile_warmed",
                fn=lambda: self.warmed,
                help="1 once warmup completed (storm detection armed)",
            )

    # -- warmup boundary ----------------------------------------------------

    def mark_warmed(self) -> None:
        """Arm storm detection: everything compiled so far was warmup
        or acknowledged cold-start; from here, a serving-path mint of
        a new program signature is a storm. A HARNESS-level
        declaration, made after the full warm set its traffic needs
        (live warm drives + the stepper's ``warm_*_buckets`` warms) —
        ``DecodeStepper.warmup()`` deliberately does not call it,
        because it covers only the step/verify families."""
        self.warmed = True

    # -- recording (called from the _jit wrapper) ---------------------------

    def record_mint(self, key: str, seconds: float, signature=(),
                    warming: bool = False, generation=None) -> dict:
        """One program mint. ``signature`` is the hashable shape/dtype
        tuple of the call's arguments — (key, signature) identity is
        what distinguishes a supervisor restart recompiling a known
        program (``rewarm``) from a genuinely new program appearing
        mid-serving (a storm candidate)."""
        sig = (str(key), signature)
        inflight = None
        fn = self.inflight_fn
        if fn is not None:
            try:
                inflight = fn()
            except Exception:  # noqa: BLE001 — observability boundary
                inflight = None
        with self._lock:
            rewarm = sig in self._seen
            self._seen.add(sig)
            trigger = "warmup" if warming else "serving"
            storm = self.warmed and not warming and not rewarm
            rec = {
                "t": time.time(),
                "key": str(key),
                "seconds": round(float(seconds), 4),
                "trigger": trigger,
                "inflight": inflight,
                "rewarm": rewarm,
                "storm": storm,
            }
            if generation is not None:
                rec["generation"] = generation
            self._records.append(rec)
            self.total += 1
            self.seconds += float(seconds)
            if warming:
                self.warmup_mints += 1
            else:
                self.serving_mints += 1
                if rewarm:
                    self.rewarms += 1
            if storm:
                self.storms += 1
        if self._compiles_counter is not None:
            self._compiles_counter.inc()
            self._seconds_counter.inc(float(seconds))
        if self.recorder is not None:
            self.recorder.record("xla.compile", **{
                k: rec[k] for k in
                ("key", "seconds", "trigger", "inflight", "rewarm")
            })
            if storm:
                # the page-now event: a compile landed on the serving
                # path AFTER warmup claimed coverage — either warmup
                # has a hole or a compile key regressed to something
                # traffic-shape-dependent
                self.recorder.record(
                    "xla.compile.storm", key=rec["key"],
                    seconds=rec["seconds"], inflight=inflight,
                )
        return rec

    # -- reading ------------------------------------------------------------

    def tail(self, n: int) -> list:
        """The most recent ``n`` mint records (newest last)."""
        if n <= 0:
            return []
        with self._lock:
            return list(self._records)[-n:]

    def mints(self) -> list:
        with self._lock:
            return list(self._records)

    def snapshot(self) -> dict:
        """The JSON-able ledger summary ``stats()`` and the soak
        summaries carry."""
        with self._lock:
            return {
                "total": self.total,
                "warmup": self.warmup_mints,
                "serving": self.serving_mints,
                "rewarms": self.rewarms,
                "storms": self.storms,
                "seconds": round(self.seconds, 4),
                "warmed": self.warmed,
                "recent": [
                    {k: r[k] for k in
                     ("key", "seconds", "trigger", "inflight",
                      "rewarm", "storm")}
                    for r in list(self._records)[-8:]
                ],
            }
