"""Windowed performance time-series over the typed-metrics registries.

Every signal PR 7/8 wired is instantaneous: the ``metrics`` verb ships
point-in-time scrape values, and an SLO verdict grades one evaluation
window. A control loop (ROADMAP item 4's autoscaler) or an operator
asking "is this replica getting WORSE" needs the dimension the scrape
throws away — time. :class:`MetricsHistory` is the sensor layer: a
bounded ring of periodic registry snapshots answering windowed
queries, all pure host arithmetic over samples that were already
being collected.

- :meth:`MetricsHistory.rate` — per-second counter rate over a
  window, RESET-AWARE: a counter that went backwards mid-window (a
  supervisor-restarted scheduler's ``fresh=True`` group starts at
  zero) contributes its post-reset total instead of a negative delta
  (the Prometheus ``increase()`` convention), so a restart can never
  produce a negative rate.
- :meth:`MetricsHistory.quantile_over` — a histogram quantile over
  ONLY the window's observations (bucket-wise increase between the
  window's edge snapshots), vs the lifetime quantile a raw sample
  gives. A latency regression five minutes old stops haunting the
  p99 an autoscaler acts on.
- :meth:`MetricsHistory.ewma` / :meth:`MetricsHistory.trend` —
  exponentially-weighted smoothing and a least-squares slope over the
  window's series: the "rising or falling, and how fast" primitives.
- :meth:`MetricsHistory.burn` — multi-window BURN-RATE evaluation of
  the existing ``SloSpec`` list (fast 1m / slow 10m, the SRE
  discipline): each spec reduces over both windows, burn = measured /
  threshold (threshold / measured for ``bound="min"`` floors), and
  the verdict distinguishes *spiking now* (fast window only — may be
  a transient), *slowly burning* (slow window only — budget eroding
  though the last minute recovered), and *breach* (both — sustained
  AND current, the page-now condition).
- :meth:`MetricsHistory.digest` — the ``timeseries`` DKT1 verb's
  payload: one row per registered series with windowed rate/value/
  quantiles, trend, and a fixed-length resampled ``points`` list
  (sparkline-ready; ``tools/dkt_top.py`` renders it).

Snapshot cadence: ``maybe_snap()`` is cadence-guarded exactly like
``SloEvaluator.maybe_evaluate`` — the engine calls it from the
supervisor thread's poll loop, the fleet router from its health
sweep, so no new thread exists anywhere. Between snaps it costs one
float compare. Defaults (1 s interval x 600 snapshots) hold ten
minutes of history — precisely the slow burn window.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

from distkeras_tpu.obs.slo import OK, SloSpec  # noqa: F401 (re-export)

#: burn-rate verdicts, in increasing severity. ``spiking`` outranks
#: ``burning``: the fast window measures what users feel RIGHT NOW.
BURN_OK, BURN_BURNING, BURN_SPIKING, BURN_BREACH = (
    "ok", "burning", "spiking", "breach"
)
_BURN_SEVERITY = {BURN_OK: 0, BURN_BURNING: 1, BURN_SPIKING: 2,
                  BURN_BREACH: 3}


def worst_burn(verdicts) -> str:
    """The most severe verdict in ``verdicts`` — the fleet-level
    reduction an autoscale policy runs over its replicas' burn
    states. ``None`` entries (a replica with no SLOs or no history
    configured) are neutral, as is anything unrecognized: absence of
    evidence never scales a fleet."""
    worst = BURN_OK
    for v in verdicts:
        if v is not None and _BURN_SEVERITY.get(v, 0) > _BURN_SEVERITY[worst]:
            worst = v
    return worst

#: the SRE-practice default windows (seconds): fast = 1 minute
#: ("spiking now"), slow = 10 minutes ("slowly burning").
FAST_WINDOW, SLOW_WINDOW = 60.0, 600.0


def _label_key(labels) -> tuple:
    return tuple(sorted(
        (str(k), str(v)) for k, v in (labels or {}).items()
    ))


class MetricsHistory:
    """Bounded ring of periodic ``MetricsRegistry`` snapshots plus the
    windowed queries over them. ``snapshot_fn`` is any callable
    returning a ``snapshot()``-shaped sample list (the engine passes
    ``metrics_snapshot``, the router ``registry.snapshot``).

    ``clock`` is injectable (``time.monotonic`` by default) so the
    edge-case tests drive resets, stale windows, and burn verdicts
    under a frozen fake clock instead of sleeping."""

    def __init__(self, snapshot_fn, interval: float = 1.0,
                 capacity: int = 600, clock=time.monotonic):
        self._snapshot_fn = snapshot_fn
        self.interval = float(interval)
        if self.interval <= 0:
            raise ValueError(f"interval must be > 0; got {interval}")
        self.capacity = int(capacity)
        if self.capacity < 2:
            raise ValueError(
                f"capacity must be >= 2 (a window needs two edges); "
                f"got {capacity}"
            )
        self._clock = clock
        # ring entries: (t, {name: [sample, ...]}) — samples grouped
        # by name in arrival order, the same index evaluate_slos builds
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._last_snap = -math.inf
        self.snaps_total = 0

    # -- collection ---------------------------------------------------------

    def snap(self) -> None:
        """Take one snapshot now (forced). A failing snapshot callable
        must never crash its host thread (the supervisor loop is also
        the watchdog) — the tick is skipped and retried next cadence."""
        now = self._clock()
        try:
            samples = self._snapshot_fn()
        except Exception:  # noqa: BLE001 — observability boundary
            return
        by_name: dict = {}
        for s in samples:
            by_name.setdefault(s["name"], []).append(s)
        with self._lock:
            self._ring.append((now, by_name))
            self._last_snap = now
            self.snaps_total += 1

    def maybe_snap(self) -> bool:
        """Snapshot at most once per ``interval`` (one float compare
        between ticks — safe to call from any poll loop)."""
        if self._clock() - self._last_snap >= self.interval:
            self.snap()
            return True
        return False

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- window selection ---------------------------------------------------

    def _window(self, window: float) -> list:
        """Ring entries inside the last ``window`` seconds, oldest
        first. A window wider than the ring's span simply returns the
        whole ring (the honest answer: everything we still know); an
        empty ring or a ring whose NEWEST entry is already older than
        the window returns [] — the queries answer None rather than
        report stale data as current."""
        now = self._clock()
        lo = now - float(window)
        with self._lock:
            entries = list(self._ring)
        if not entries or entries[-1][0] < lo:
            return []
        return [e for e in entries if e[0] >= lo]

    @staticmethod
    def _pick(by_name: dict, name: str, labels: dict | None):
        """The sample a query reads from one snapshot: first sample
        under ``name`` whose labels CONTAIN ``labels`` (None/empty =
        the first sample, mirroring the SLO evaluator)."""
        want = labels or {}
        for s in by_name.get(name, ()):
            have = s.get("labels") or {}
            if all(have.get(k) == v for k, v in want.items()):
                return s
        return None

    def series(self, name: str, window: float,
               labels: dict | None = None) -> list:
        """``[(t, value), ...]`` of the sample's scalar value over the
        window (counters and gauges; histogram samples yield their
        observation ``count``). Points where the series is missing or
        the value is None are skipped."""
        out = []
        for t, by_name in self._window(window):
            s = self._pick(by_name, name, labels)
            if s is None:
                continue
            v = s.get("value") if "value" in s else s.get("count")
            if v is None:
                continue
            out.append((t, float(v)))
        return out

    # -- windowed reductions ------------------------------------------------

    @staticmethod
    def _increase(points) -> float | None:
        """Reset-aware monotonic increase over ``[(t, v), ...]``: sum
        of consecutive deltas, where a NEGATIVE delta (counter reset —
        a rebuilt scheduler generation starts its ``fresh`` counters
        at zero) contributes the post-reset value instead (the counter
        counted at least that much since the reset). Never negative."""
        if len(points) < 2:
            return None
        inc = 0.0
        for (_, a), (_, b) in zip(points, points[1:]):
            inc += (b - a) if b >= a else b
        return max(0.0, inc)

    def increase(self, name: str, window: float,
                 labels: dict | None = None) -> float | None:
        return self._increase(self.series(name, window, labels))

    def rate(self, name: str, window: float,
             labels: dict | None = None) -> float | None:
        """Per-second counter rate over the window (increase /
        elapsed). None when the window holds fewer than two
        snapshots — an empty or stale window is "unknown", never 0."""
        points = self.series(name, window, labels)
        inc = self._increase(points)
        if inc is None:
            return None
        dt = points[-1][0] - points[0][0]
        if dt <= 0:
            return None
        return inc / dt

    def mean_over(self, name: str, window: float,
                  labels: dict | None = None) -> float | None:
        """Windowed mean of a gauge's sampled values."""
        points = self.series(name, window, labels)
        if not points:
            return None
        return sum(v for _, v in points) / len(points)

    def _hist_window(self, name, window, labels):
        """Bucket-wise increase of a histogram over the window:
        ``(delta_buckets, delta_count, delta_sum)`` where buckets are
        ``[le, cumulative_delta]`` rows. Reset-aware: any bucket
        running backwards means the histogram was rebuilt mid-window,
        and the LAST snapshot alone (everything since the reset) is
        the window's honest content. A window holding a SINGLE
        snapshot answers None, like ``rate``: one edge cannot bound an
        increase, and returning the lifetime distribution would report
        an hours-old spike as the window's content (the staleness a
        query-cadenced ring — a standby PS, a predict-only engine —
        would otherwise serve)."""
        entries = self._window(window)
        first = last = None
        for _, by_name in entries:
            s = self._pick(by_name, name, labels)
            if s is None or "buckets" not in s:
                continue
            if first is None:
                first = s
            last = s
        return self._hist_delta(first, last)

    @staticmethod
    def _hist_delta(first, last):
        """The bucket-wise increase between a window's edge histogram
        samples (the reduction behind ``_hist_window``, factored so
        ``digest``'s one-pass collection shares it). None when the
        window holds fewer than two samples."""
        if last is None or first is last or first is None:
            return None
        old = {
            str(le): float(c) for le, c in first.get("buckets", ())
        }
        delta, reset = [], False
        for le, c in last["buckets"]:
            d = float(c) - old.get(str(le), 0.0)
            if d < 0:
                reset = True
                break
            delta.append([le, d])
        if reset:
            delta = [[le, float(c)] for le, c in last["buckets"]]
            return delta, int(last.get("count", 0)), float(
                last.get("sum", 0.0)
            )
        count = int(last.get("count", 0)) - int(first.get("count", 0))
        total = float(last.get("sum", 0.0)) - float(
            first.get("sum", 0.0)
        )
        if count < 0:
            count, total = int(last.get("count", 0)), float(
                last.get("sum", 0.0)
            )
        return delta, count, total

    def quantile_over(self, name: str, window: float, q: float,
                      labels: dict | None = None) -> float | None:
        """Bucket-resolution quantile over ONLY the window's
        observations (the windowed sibling of ``Histogram.quantile``).
        None when the window saw no observations."""
        return self._quantile_from_delta(
            self._hist_window(name, window, labels), q
        )

    @staticmethod
    def _quantile_from_delta(hw, q: float) -> float | None:
        """Quantile out of a ``_hist_delta`` result (shared by
        ``quantile_over`` and ``digest``'s one-pass rows)."""
        if hw is None:
            return None
        delta, count, _ = hw
        if count < 1:
            return None
        target = max(1, int(q * count))
        last_finite = None
        for le, cum in delta:
            if le != "+Inf":
                last_finite = float(le)
            if cum >= target:
                return last_finite
        return last_finite

    def hist_stats(self, name: str, window: float,
                   labels: dict | None = None) -> dict | None:
        """Windowed histogram digest: observation count, per-second
        observation rate, mean, p50, p99."""
        hw = self._hist_window(name, window, labels)
        if hw is None:
            return None
        delta, count, total = hw
        points = self.series(name, window, labels)
        dt = points[-1][0] - points[0][0] if len(points) >= 2 else 0.0
        return {
            "count": count,
            "rate": round(count / dt, 4) if dt > 0 else None,
            "mean": round(total / count, 6) if count else None,
            "p50": self.quantile_over(name, window, 0.5, labels),
            "p99": self.quantile_over(name, window, 0.99, labels),
        }

    # -- smoothing / trend --------------------------------------------------

    @staticmethod
    def _ewma(points, halflife: float) -> float | None:
        """EWMA of ``[(t, v), ...]`` with a time-aware decay (irregular
        snapshot spacing decays by real elapsed time, not sample
        count)."""
        if not points:
            return None
        ew = points[0][1]
        for (t0, _), (t1, v) in zip(points, points[1:]):
            a = 1.0 - 0.5 ** (max(0.0, t1 - t0) / max(halflife, 1e-9))
            ew = ew + a * (v - ew)
        return ew

    def ewma(self, name: str, window: float,
             halflife: float | None = None,
             labels: dict | None = None) -> float | None:
        """EWMA-smoothed latest value of a gauge series (halflife
        defaults to window/10 — recent-minute-weighted)."""
        hl = halflife if halflife is not None else float(window) / 10.0
        return self._ewma(self.series(name, window, labels), hl)

    @staticmethod
    def _slope(points) -> float | None:
        """Least-squares slope (units/second) over ``[(t, v), ...]`` —
        the trend direction dkt_top renders as an arrow and a control
        loop compares against zero."""
        if len(points) < 2:
            return None
        t0 = points[0][0]
        xs = [t - t0 for t, _ in points]
        ys = [v for _, v in points]
        n = len(points)
        mx, my = sum(xs) / n, sum(ys) / n
        den = sum((x - mx) ** 2 for x in xs)
        if den <= 0:
            return None
        return sum(
            (x - mx) * (y - my) for x, y in zip(xs, ys)
        ) / den

    def trend(self, name: str, window: float,
              labels: dict | None = None) -> float | None:
        """Slope of the series over the window (per second). For
        counters, call on the rate points via ``digest`` instead —
        a lifetime counter's raw slope IS its rate."""
        return self._slope(self.series(name, window, labels))

    # -- burn-rate SLO evaluation -------------------------------------------

    def _reduce_windowed(self, spec: SloSpec, window: float):
        """Reduce one spec's series over ``window``: ``(value, count)``
        with value None = not judgeable, mirroring
        ``slo._reduce`` but windowed — ``rate`` aggs become the ratio
        of windowed INCREASES (errors this window / submissions this
        window), quantile/mean aggs read only the window's
        observations, and ``value`` aggs take the windowed mean."""
        if spec.agg == "value":
            v = self.mean_over(spec.series, window, spec.labels)
            return v, (1 if v is not None else 0)
        if spec.agg in ("p50", "p99"):
            q = 0.5 if spec.agg == "p50" else 0.99
            hw = self._hist_window(spec.series, window, spec.labels)
            if hw is None:
                return None, 0
            _, count, _ = hw
            return (
                self.quantile_over(spec.series, window, q, spec.labels),
                count,
            )
        if spec.agg == "mean":
            hw = self._hist_window(spec.series, window, spec.labels)
            if hw is None:
                return None, 0
            _, count, total = hw
            if not count:
                return None, 0
            return total / count, count
        # rate: windowed numerator increase / windowed denominator
        # increase — both sides reset-aware
        num = self.increase(spec.series, window, spec.labels)
        den = self.increase(spec.per, window, spec.labels)
        if num is None or not den:
            return None, 0
        return num / den, int(den)

    @staticmethod
    def _burn_of(spec: SloSpec, value) -> float | None:
        """Burn rate = how fast the spec's budget is being consumed:
        1.0 means exactly at threshold. ``bound="max"``: measured /
        threshold; ``bound="min"`` (floors): threshold / measured —
        a measured value at half the floor burns at 2x either way."""
        if value is None:
            return None
        if spec.bound == "max":
            if spec.threshold <= 0:
                return math.inf if value > 0 else 0.0
            return value / spec.threshold
        if value <= 0:
            return math.inf if spec.threshold > 0 else 0.0
        return spec.threshold / value

    def burn(self, specs, fast: float = FAST_WINDOW,
             slow: float = SLOW_WINDOW) -> dict:
        """Multi-window burn-rate verdict over ``specs`` (the SAME
        ``SloSpec`` list the point-in-time evaluator grades). Per
        spec: ``breach`` when BOTH windows burn >= 1 (sustained and
        current — page now), ``spiking`` when only the fast window
        does (happening right now; may be a transient), ``burning``
        when only the slow window does (the budget is eroding though
        the last minute looks fine), ``ok`` otherwise. Windows with
        too little data (under ``min_count``, or no snapshots) never
        judge — unknown is not violated."""
        rows, violations = [], []
        worst = BURN_OK
        for spec in specs:
            fv, fc = self._reduce_windowed(spec, fast)
            sv, sc = self._reduce_windowed(spec, slow)
            fb = (
                self._burn_of(spec, fv)
                if fc >= spec.min_count else None
            )
            sb = (
                self._burn_of(spec, sv)
                if sc >= spec.min_count else None
            )
            f_hot = fb is not None and fb >= 1.0
            s_hot = sb is not None and sb >= 1.0
            if f_hot and s_hot:
                verdict = BURN_BREACH
            elif f_hot:
                verdict = BURN_SPIKING
            elif s_hot:
                verdict = BURN_BURNING
            else:
                verdict = BURN_OK

            def _r(x):
                if x is None:
                    return None
                return round(x, 4) if math.isfinite(x) else "inf"

            row = {
                "name": spec.name,
                "series": spec.series,
                "agg": spec.agg,
                "threshold": spec.threshold,
                "fast_value": _r(fv),
                "slow_value": _r(sv),
                "fast_burn": _r(fb),
                "slow_burn": _r(sb),
                "verdict": verdict,
            }
            if spec.labels:
                row["labels"] = dict(spec.labels)
            rows.append(row)
            if verdict != BURN_OK:
                violations.append({
                    k: row[k] for k in
                    ("name", "series", "fast_burn", "slow_burn",
                     "verdict")
                })
            if _BURN_SEVERITY[verdict] > _BURN_SEVERITY[worst]:
                worst = verdict
        return {
            "burn": worst,
            "windows": {"fast": float(fast), "slow": float(slow)},
            "violations": violations,
            "specs": rows,
        }

    # -- the timeseries-verb digest -----------------------------------------

    def _resample(self, points, window: float, nbuckets: int,
                  counter: bool) -> list:
        """Fixed-length resample of a series for sparklines: the
        window splits into ``nbuckets`` equal time buckets; counters
        yield each bucket's per-second increase (reset-aware), gauges
        the bucket mean (empty buckets carry None)."""
        if not points or nbuckets < 1:
            return []
        now = self._clock()
        lo = now - float(window)
        width = float(window) / nbuckets
        buckets: list[list] = [[] for _ in range(nbuckets)]
        for t, v in points:
            i = min(nbuckets - 1, max(0, int((t - lo) / width)))
            buckets[i].append((t, v))
        out = []
        prev_last = None
        for b in buckets:
            if not b:
                out.append(None)
                continue
            if counter:
                pts = ([prev_last] if prev_last is not None else []) + b
                inc = self._increase(pts)
                dt = pts[-1][0] - pts[0][0]
                out.append(
                    round(inc / dt, 4)
                    if inc is not None and dt > 0 else None
                )
            else:
                out.append(round(sum(v for _, v in b) / len(b), 4))
            prev_last = b[-1]
        return out

    def digest(self, window: float = FAST_WINDOW, names=None,
               points: int = 30) -> dict:
        """The ``timeseries`` verb's payload: one row per registered
        series with its windowed reduction, trend, and sparkline
        points. ``names``: optional iterable restricting which series
        are reported (a dashboard polling one panel must not pay for
        the whole registry). ONE pass over the window builds every
        series' point list (and histograms' edge samples) — the
        per-row query methods would each re-copy the ring, turning a
        72-row digest into hundreds of ring walks on the conn
        thread."""
        entries = self._window(window)
        want = None if names is None else set(names)
        # (name, label_key) -> collected state, insertion-ordered
        col: dict = {}
        for t, by_name in entries:
            for name, samples in by_name.items():
                if want is not None and name not in want:
                    continue
                for s in samples:
                    key = (name, _label_key(s.get("labels")))
                    st = col.get(key)
                    if st is None:
                        st = col[key] = {
                            "sample": s, "pts": [],
                            "hfirst": None, "hlast": None,
                        }
                    v = s.get("value") if "value" in s else s.get(
                        "count"
                    )
                    if v is not None:
                        st["pts"].append((t, float(v)))
                    if "buckets" in s:
                        if st["hfirst"] is None:
                            st["hfirst"] = s
                        st["hlast"] = s
        rows = [
            self._digest_row(st, window, points)
            for st in col.values()
        ]
        return {
            "window": float(window),
            "interval": self.interval,
            "snapshots": len(entries),
            "points": int(points),
            "series": rows,
        }

    def _digest_row(self, st, window, npoints) -> dict:
        sample = st["sample"]
        name = sample["name"]
        labels = dict(sample.get("labels") or {})
        kind = sample["kind"]
        row = {"name": name, "labels": labels, "kind": kind}
        pts = st["pts"]
        if kind == "counter":
            inc = self._increase(pts)
            dt = pts[-1][0] - pts[0][0] if len(pts) >= 2 else 0.0
            row["rate"] = (
                inc / dt if inc is not None and dt > 0 else None
            )
            row["increase"] = inc
            rp = self._resample(pts, window, npoints, counter=True)
            row["points"] = rp
            row["trend"] = self._slope([
                (i, v) for i, v in enumerate(rp) if v is not None
            ])
        elif kind == "gauge":
            row["value"] = pts[-1][1] if pts else None
            row["mean"] = (
                sum(v for _, v in pts) / len(pts) if pts else None
            )
            row["ewma"] = self._ewma(pts, float(window) / 10.0)
            row["trend"] = self._slope(pts)
            row["points"] = self._resample(
                pts, window, npoints, counter=False
            )
        else:  # histogram
            hw = self._hist_delta(st["hfirst"], st["hlast"])
            if hw is not None:
                _, count, total = hw
                dt = (
                    pts[-1][0] - pts[0][0] if len(pts) >= 2 else 0.0
                )
                row.update({
                    "count": count,
                    "rate": round(count / dt, 4) if dt > 0 else None,
                    "mean": (
                        round(total / count, 6) if count else None
                    ),
                    "p50": self._quantile_from_delta(hw, 0.5),
                    "p99": self._quantile_from_delta(hw, 0.99),
                })
            rp = self._resample(pts, window, npoints, counter=True)
            row["points"] = rp  # per-second observation rate
            row["trend"] = self._slope([
                (i, v) for i, v in enumerate(rp) if v is not None
            ])
        if row.get("trend") is not None:
            row["trend"] = round(row["trend"], 6)
        for k in ("rate", "increase", "value", "mean", "ewma"):
            if row.get(k) is not None:
                row[k] = round(float(row[k]), 6)
        return row
