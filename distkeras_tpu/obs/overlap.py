"""Overlap ledger — the zero-bubble decode instrumentation.

The serving loop alternates host scheduling work (admission, chunked
prefill, QoS decisions, page bookkeeping, stream pushes) with the
compiled device step. Sequentially those phases add; with async
dispatch they overlap, and the *bubble* — iteration wall-clock the
device spent idle waiting on the host — is the number the overlap
refactor exists to shrink. This ledger makes it a first-class,
time-series-visible metric instead of a one-off bench printout:

- ``serving_step_bubble_seconds`` (histogram): per scheduler
  iteration, ``iteration_wall - device_wall`` clipped at zero, where
  iteration wall is collect-to-collect and device wall is
  dispatch-to-ready for that iteration's step.
- ``serving_overlap_efficiency`` (gauge): cumulative
  ``device_seconds / iteration_seconds`` — the fraction of decode
  wall-clock the device was actually computing (1.0 = zero bubble).
  ``1 - efficiency`` is the bubble fraction ``dkt_top`` renders.

The batcher stamps three instants per iteration through this ledger:
``note_dispatch()`` when the compiled call is issued,
``note_ready()`` when device completion is first *observed* (an
opportunistic poll between host phases, or implicitly at collect),
and ``note_collect()`` when the tokens are materialized. Device wall
is measured, not inferred: if readiness was never observed before the
blocking collect, the device ran right up to the collect and the
bubble for that interval is honestly zero. The clock is injectable so
the arithmetic is unit-testable without sleeping.

Both loop modes feed the same ledger — the sequential control stamps
dispatch/ready/collect back-to-back around its blocking step, so the
committed overlapped-vs-sequential A/B reads the bubble from the same
instrument on both sides.
"""

from __future__ import annotations

import time


class OverlapLedger:
    """Per-iteration dispatch/ready/collect bookkeeping over a
    ``MetricsRegistry``. Single-writer (the scheduler thread); the
    gauge callback tolerates a torn read like every other scrape."""

    def __init__(self, registry, clock=time.monotonic):
        self._clock = clock
        # 1 µs .. ~67 s: decode bubbles on a warm CPU engine are
        # tens of microseconds; a compile stall is tens of seconds
        self.bubble = registry.histogram(
            "serving_step_bubble_seconds",
            help="per-iteration host bubble: iteration wall minus "
                 "device wall",
            start=1e-6, factor=2.0, num_buckets=26,
        )
        registry.gauge(
            "serving_overlap_efficiency",
            help="cumulative device_wall / iteration_wall (1.0 = "
                 "zero bubble)",
            fn=lambda: self.efficiency,
        )
        self.iterations = 0
        self.device_seconds = 0.0
        self.iteration_seconds = 0.0
        self._dispatched_at = None
        self._ready_at = None
        self._last_collect = None

    # -- the three stamps (scheduler thread only) ---------------------------

    def note_dispatch(self) -> None:
        """The compiled step for this iteration was just issued."""
        self._dispatched_at = self._clock()
        self._ready_at = None

    def note_ready(self) -> None:
        """Device completion observed (first observation wins — later
        polls and the implicit collect stamp never move it back)."""
        if self._ready_at is None and self._dispatched_at is not None:
            self._ready_at = self._clock()

    def note_collect(self) -> None:
        """Tokens materialized: close this iteration's ledger entry.
        No-op when nothing was dispatched (idle scheduler passes)."""
        now = self._clock()
        if self._dispatched_at is None:
            return
        ready = self._ready_at if self._ready_at is not None else now
        device = min(max(0.0, ready - self._dispatched_at),
                     max(0.0, now - self._dispatched_at))
        # iteration wall: collect-to-collect once steady, else
        # dispatch-to-collect (the first iteration has no predecessor)
        base = (
            self._last_collect
            if self._last_collect is not None
            and self._last_collect <= self._dispatched_at
            else self._dispatched_at
        )
        iter_wall = max(0.0, now - base)
        device = min(device, iter_wall)
        self.bubble.observe(iter_wall - device)
        self.iterations += 1
        self.device_seconds += device
        self.iteration_seconds += iter_wall
        self._dispatched_at = None
        self._ready_at = None
        self._last_collect = now

    def discard(self) -> None:
        """Drop an in-flight entry without closing it (the step was
        abandoned — scheduler stop with a handle still in the air)."""
        self._dispatched_at = None
        self._ready_at = None

    # -- read side ----------------------------------------------------------

    @property
    def efficiency(self):
        """Cumulative device/iteration wall fraction; None before the
        first completed iteration (a gauge gap, not a fake 0 or 1)."""
        if self.iteration_seconds <= 0.0:
            return None
        return min(1.0, self.device_seconds / self.iteration_seconds)

    @property
    def bubble_fraction(self):
        """``1 - efficiency``; None before the first iteration."""
        eff = self.efficiency
        return None if eff is None else 1.0 - eff

    def snapshot(self) -> dict:
        """JSON-able summary for ``health``/bench blocks."""
        eff = self.efficiency
        return {
            "iterations": self.iterations,
            "device_seconds": round(self.device_seconds, 6),
            "iteration_seconds": round(self.iteration_seconds, 6),
            "efficiency": None if eff is None else round(eff, 4),
            "bubble_fraction": (
                None if eff is None else round(1.0 - eff, 4)
            ),
        }
