"""End-to-end request tracing across the serving fleet (Dapper-style).

A request now crosses up to five hops — client -> ``FleetRouter`` ->
``ServingServer`` -> ``ContinuousBatcher`` -> ``DecodeStepper`` — and
when the fleet soak ejects a replica or blame-quarantines a slot, the
question "where did request X spend its time, and which hop failed it"
used to take four logs to answer. This module is the propagated trace
context plus span recording that answers it in one place:

- :class:`TraceContext` — ``(trace_id, span_id, parent_id)`` plus a
  ``want_timeline`` flag, carried in an OPTIONAL ``trace`` field of
  the DKT1 frame header (:meth:`TraceContext.to_wire` /
  :meth:`TraceContext.from_wire`). Requests without the field cost
  one dict lookup — tracing is strictly opt-in per request.
- :class:`Span` — one timed operation; ``end()`` freezes it into a
  JSON-able record and hands it to the collector. A span marked
  ``terminal=True`` states the request's final outcome (``status`` is
  ``"ok"`` or the typed wire error code) — a COMPLETE trace is one
  with exactly such an ending, which is what the soaks assert for
  every attempt.
- :class:`TraceCollector` — process-wide bounded ring of finished
  span records; ``drain_to(MetricsLogger)`` flushes them to the
  existing JSONL sink (``utils.profiling``), one ``trace_span`` event
  per line, so traces land next to the metrics events that already
  live there.
- :func:`request_spans` — builds the server-side timeline of one
  ``ServeRequest`` (queue wait, prefill with per-chunk child spans,
  decode aggregated over iterations) from the timestamps and event
  ledger the scheduler already keeps; the server attaches it to the
  reply when the client asked ``trace=True``.

Span hierarchy of a routed generate (see docs/ARCHITECTURE.md):

    client.request                       (client; terminal)
      router.route                       (router: affinity/spill/
                                          failover decisions)
        server.generate                  (server dispatch->reply)
          serving.queue                  (submit -> slot admission)
          serving.prefill                (admission -> decodable)
            serving.prefill_chunk ...    (one per chunk)
          serving.decode                 (decodable -> finished;
                                          iterations aggregated)
          scheduler.blame                (only when a device failure
                                          was blamed on this request)
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque


def new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


class TraceContext:
    """Propagated identity of one request's trace. ``child()`` derives
    the context a downstream hop records its spans under (fresh
    span_id, parent = this hop's span)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "want_timeline")

    def __init__(self, trace_id=None, span_id=None, parent_id=None,
                 want_timeline=False):
        self.trace_id = trace_id or new_id()
        self.span_id = span_id or new_id()
        self.parent_id = parent_id
        self.want_timeline = bool(want_timeline)

    @classmethod
    def new(cls, want_timeline=False) -> "TraceContext":
        return cls(want_timeline=want_timeline)

    def child(self) -> "TraceContext":
        return TraceContext(
            self.trace_id, new_id(), self.span_id, self.want_timeline
        )

    # -- wire ---------------------------------------------------------------

    def to_wire(self) -> dict:
        """The optional DKT1 header field (``header["trace"]``)."""
        d = {"id": self.trace_id, "span": self.span_id}
        if self.parent_id is not None:
            d["parent"] = self.parent_id
        if self.want_timeline:
            d["return"] = True
        return d

    @classmethod
    def from_wire(cls, field) -> "TraceContext | None":
        """Parse ``header.get("trace")``; None (absent/malformed) means
        the request is untraced — a garbled field must never fail a
        request over an observability frill."""
        if not isinstance(field, dict) or not field.get("id"):
            return None
        return cls(
            trace_id=str(field["id"]),
            span_id=str(field.get("span") or new_id()),
            parent_id=(
                str(field["parent"]) if field.get("parent") else None
            ),
            want_timeline=bool(field.get("return")),
        )


class Span:
    """One timed operation under a trace. Created open via
    :func:`start_span`; ``end()`` freezes and records it. The record
    is a flat JSON-able dict (what rides replies and the JSONL sink).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "attrs", "_collector", "record")

    def __init__(self, name, ctx: TraceContext, collector, **attrs):
        self.name = name
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id
        self.parent_id = ctx.parent_id
        self.start = time.time()
        self.attrs = attrs
        self._collector = collector
        self.record = None

    def end(self, status: str = "ok", terminal: bool = False,
            **attrs) -> dict:
        if self.record is not None:
            return self.record  # idempotent: a span ends once
        self.attrs.update(attrs)
        self.record = span_record(
            self.name, self.trace_id, self.span_id, self.parent_id,
            self.start, time.time() - self.start, status=status,
            terminal=terminal, **self.attrs,
        )
        if self._collector is not None:
            self._collector.record(self.record)
        return self.record


def span_record(name, trace_id, span_id, parent_id, start, duration_s,
                status="ok", terminal=False, **attrs) -> dict:
    """A finished span as a flat dict — the one schema every producer
    (live spans, the scheduler's event ledger, reconstructed request
    timelines) emits."""
    rec = {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "start": round(float(start), 6),
        "duration_ms": round(max(0.0, float(duration_s)) * 1e3, 3),
        "status": status,
    }
    if terminal:
        rec["terminal"] = True
    if attrs:
        rec["attrs"] = attrs
    return rec


class TraceCollector:
    """Bounded, thread-safe ring of finished span records. Keeps the
    most recent ``capacity`` spans; ``dropped`` counts what the bound
    discarded (never silently — the JSONL drain records it).

    ``on_drop``: optional zero-arg callback fired ONCE, on the ring's
    first-ever drop (the 0 -> nonzero transition of
    ``dropped_total``). The engine wires it to a ``trace.drops``
    flight-recorder event so silent span loss under load is on the
    incident tape, not only a gauge nobody watches; called outside
    the collector lock (the recorder takes its own)."""

    def __init__(self, capacity: int = 8192, on_drop=None):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(
                f"capacity must be >= 1; got {capacity}"
            )
        self._spans: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.on_drop = on_drop
        self.dropped = 0
        # lifetime total: ``dropped`` is read-and-reset by the JSONL
        # drain, so a scrape-time gauge over it would zero whenever the
        # sink flushed — this one only grows
        self.dropped_total = 0

    def record(self, span: dict) -> None:
        first_drop = False
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
                self.dropped_total += 1
                first_drop = self.dropped_total == 1
            self._spans.append(span)
        if first_drop and self.on_drop is not None:
            try:
                self.on_drop()
            except Exception:  # noqa: BLE001 — observability boundary
                pass

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            return out

    def spans_for(self, trace_id: str) -> list[dict]:
        with self._lock:
            return [s for s in self._spans if s["trace_id"] == trace_id]

    def drain_to(self, metrics_logger) -> int:
        """Flush everything into a ``utils.profiling.MetricsLogger``
        (one ``trace_span`` JSONL event per span); returns the number
        of spans written. The drop counter is read-and-reset UNDER the
        lock with the spans, so a drop landing mid-drain is reported
        by the next flush instead of silently zeroed."""
        with self._lock:
            spans = list(self._spans)
            self._spans.clear()
            dropped, self.dropped = self.dropped, 0
        for s in spans:
            metrics_logger.log(event="trace_span", **s)
        if dropped:
            metrics_logger.log(
                event="trace_spans_dropped", dropped=dropped
            )
        return len(spans)


#: process-wide default collector — servers/routers/schedulers record
#: here; ``ServingEngine`` drains it to its MetricsLogger when one is
#: configured, and tools read it in-process.
COLLECTOR = TraceCollector()


def start_span(name, ctx: TraceContext, collector=COLLECTOR,
               **attrs) -> Span:
    return Span(name, ctx, collector, **attrs)


def stamp_error_trace(reply_header: dict, request_header: dict,
                      exc) -> None:
    """Stamp trace identity onto a typed ERROR reply so client-side
    failures join server-side spans: prefer the full trace a traced
    ``generate`` attached to the exception (``exc.trace`` — id plus
    any timeline), else echo the request header's trace id. Untraced
    requests leave the reply untouched."""
    tr = getattr(exc, "trace", None)
    if tr is None:
        field = request_header.get("trace")
        if isinstance(field, dict) and field.get("id"):
            tr = {"id": str(field["id"])}
    if tr is not None:
        reply_header["trace"] = tr


def timeline_complete(spans) -> bool:
    """A trace is COMPLETE when exactly one span states the final
    outcome — what the soaks assert for every attempt (completed,
    typed-error, or failed-over alike)."""
    return sum(1 for s in spans if s.get("terminal")) == 1


def request_spans(req, ctx: TraceContext, collector=COLLECTOR) -> list[dict]:
    """The server-side phase timeline of one finished ``ServeRequest``,
    reconstructed from the timestamps and per-request event ledger the
    scheduler records (monotonic clocks converted to wall time): queue
    wait, prefill (+ one child span per prefill chunk), decode
    (iterations aggregated), plus a ``scheduler.blame`` span when a
    device failure was blamed on this request. Spans are parented
    under ``ctx`` (the server's own span) and also pushed to the
    collector."""
    # map the request's monotonic stamps onto the wall clock
    off = time.time() - time.monotonic()
    out = []

    def phase(name, t0, t1, **attrs):
        rec = span_record(
            name, ctx.trace_id, new_id(), ctx.span_id,
            off + t0, t1 - t0, **attrs,
        )
        out.append(rec)
        if collector is not None:
            collector.record(rec)
        return rec

    if req.started is not None:
        phase("serving.queue", req.created, req.started)
    if req.started is not None and req.prefill_finished is not None:
        pf = phase("serving.prefill", req.started, req.prefill_finished,
                   chunks=int(req.prefill_chunks))
        for ev in req.events:
            if ev["name"] != "serving.prefill_chunk":
                continue
            rec = span_record(
                ev["name"], ctx.trace_id, new_id(), pf["span_id"],
                off + ev["t0"], ev["t1"] - ev["t0"],
                **{k: v for k, v in ev.items()
                   if k not in ("name", "t0", "t1")},
            )
            out.append(rec)
            if collector is not None:
                collector.record(rec)
    if req.prefill_finished is not None and req.finished is not None:
        dec = phase(
            "serving.decode", req.prefill_finished, req.finished,
            iterations=int(req.iterations), tokens=len(req.tokens),
        )
        for ev in req.events:
            # streaming delivery: one child span per chunk frame the
            # server flushed (the per-chunk trace of the streaming
            # generate verb), parented under the decode phase
            if ev["name"] != "serving.stream_chunk":
                continue
            rec = span_record(
                ev["name"], ctx.trace_id, new_id(), dec["span_id"],
                off + ev["t0"], ev["t1"] - ev["t0"],
                **{k: v for k, v in ev.items()
                   if k not in ("name", "t0", "t1")},
            )
            out.append(rec)
            if collector is not None:
                collector.record(rec)
    for ev in req.events:
        if ev["name"] == "scheduler.blame":
            phase(
                "scheduler.blame", ev["t0"], ev["t1"],
                status="internal", slot=ev.get("slot"),
            )
        elif ev["name"] == "xla.compile":
            # a program mint landed inside this traced request's
            # lifetime (compile-ledger attribution in the scheduler):
            # the stall is VISIBLE in the client-assembled timeline —
            # exactly the class the r14/r16 bench post-mortems hit
            # blind
            phase(
                "xla.compile", ev["t0"], ev["t1"],
                **{k: v for k, v in ev.items()
                   if k not in ("name", "t0", "t1")},
            )
    return out
