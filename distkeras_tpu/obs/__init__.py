"""Observability: tracing + typed metrics + the failure-path black box.

Four pillars, wired through every tier of the stack (client, fleet
router, serving server, scheduler, engine, prefix cache, parameter
servers):

- ``tracing``: a Dapper-style :class:`TraceContext` propagated in an
  optional ``trace`` field of the DKT1 frame header, with
  :class:`Span` records collected process-wide and (opt-in per
  request) assembled into a per-request timeline on the reply. See
  docs/ARCHITECTURE.md "Observability" for the span hierarchy.
- ``metrics``: Prometheus-style :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` in a :class:`MetricsRegistry`, replacing the
  hand-rolled per-component counter dicts (:class:`CounterGroup` keeps
  the ``counters["key"] += 1`` call sites working verbatim); exposed
  by the ``metrics`` DKT1 verb and renderable as the Prometheus text
  exposition format (``render_prometheus`` / ``parse_prometheus``).
- ``recorder``: the always-on :class:`FlightRecorder` ring of
  component events (scheduler iterations, blame/quarantine, watchdog
  trips, router ejections, PS replication/promotion, armed fault-seam
  firings) plus :func:`dump_postmortem` — the one bundle writer every
  self-healing seam dumps through on a terminal event, retrieved by
  the ``postmortem`` DKT1 verb and rendered by
  ``tools/dkt_postmortem.py``.
- ``slo``: declarative :class:`SloSpec` objectives evaluated from the
  registries (:func:`evaluate_slos` / :class:`SloEvaluator`); verdicts
  (``ok``/``warn``/``breach``) ride the ``health`` verb, breaches land
  in the recorder and a registry counter, and the fleet health sweep
  can eject on sustained breach.
"""

from distkeras_tpu.obs.recorder import (
    POSTMORTEM_SCHEMA,
    FlightRecorder,
    build_postmortem,
    dump_postmortem,
    latest_postmortem,
)
from distkeras_tpu.obs.slo import (
    SloEvaluator,
    SloSpec,
    default_serving_slos,
    default_training_slos,
    evaluate_slos,
)
from distkeras_tpu.obs.metrics import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    MetricsRegistry,
    label_samples,
    parse_prometheus,
    render_prometheus,
)
from distkeras_tpu.obs.tracing import (
    COLLECTOR,
    Span,
    TraceCollector,
    TraceContext,
    new_id,
    request_spans,
    span_record,
    stamp_error_trace,
    start_span,
    timeline_complete,
)

__all__ = [
    "COLLECTOR",
    "POSTMORTEM_SCHEMA",
    "Counter",
    "CounterGroup",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SloEvaluator",
    "SloSpec",
    "Span",
    "TraceCollector",
    "TraceContext",
    "build_postmortem",
    "default_serving_slos",
    "default_training_slos",
    "dump_postmortem",
    "evaluate_slos",
    "latest_postmortem",
    "label_samples",
    "new_id",
    "parse_prometheus",
    "render_prometheus",
    "request_spans",
    "span_record",
    "stamp_error_trace",
    "start_span",
    "timeline_complete",
]
