"""Observability: end-to-end request tracing + the typed metrics registry.

Two pillars, wired through every tier of the stack (client, fleet
router, serving server, scheduler, engine, prefix cache, parameter
servers):

- ``tracing``: a Dapper-style :class:`TraceContext` propagated in an
  optional ``trace`` field of the DKT1 frame header, with
  :class:`Span` records collected process-wide and (opt-in per
  request) assembled into a per-request timeline on the reply. See
  docs/ARCHITECTURE.md "Observability" for the span hierarchy.
- ``metrics``: Prometheus-style :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` in a :class:`MetricsRegistry`, replacing the
  hand-rolled per-component counter dicts (:class:`CounterGroup` keeps
  the ``counters["key"] += 1`` call sites working verbatim); exposed
  by the ``metrics`` DKT1 verb and renderable as the Prometheus text
  exposition format (``render_prometheus`` / ``parse_prometheus``).
"""

from distkeras_tpu.obs.metrics import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    MetricsRegistry,
    label_samples,
    parse_prometheus,
    render_prometheus,
)
from distkeras_tpu.obs.tracing import (
    COLLECTOR,
    Span,
    TraceCollector,
    TraceContext,
    new_id,
    request_spans,
    span_record,
    stamp_error_trace,
    start_span,
    timeline_complete,
)

__all__ = [
    "COLLECTOR",
    "Counter",
    "CounterGroup",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "TraceCollector",
    "TraceContext",
    "label_samples",
    "new_id",
    "parse_prometheus",
    "render_prometheus",
    "request_spans",
    "span_record",
    "stamp_error_trace",
    "start_span",
    "timeline_complete",
]
