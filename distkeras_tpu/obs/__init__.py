"""Observability: tracing + typed metrics + the failure-path black box.

Four pillars, wired through every tier of the stack (client, fleet
router, serving server, scheduler, engine, prefix cache, parameter
servers):

- ``tracing``: a Dapper-style :class:`TraceContext` propagated in an
  optional ``trace`` field of the DKT1 frame header, with
  :class:`Span` records collected process-wide and (opt-in per
  request) assembled into a per-request timeline on the reply. See
  docs/ARCHITECTURE.md "Observability" for the span hierarchy.
- ``metrics``: Prometheus-style :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` in a :class:`MetricsRegistry`, replacing the
  hand-rolled per-component counter dicts (:class:`CounterGroup` keeps
  the ``counters["key"] += 1`` call sites working verbatim); exposed
  by the ``metrics`` DKT1 verb and renderable as the Prometheus text
  exposition format (``render_prometheus`` / ``parse_prometheus``).
- ``recorder``: the always-on :class:`FlightRecorder` ring of
  component events (scheduler iterations, blame/quarantine, watchdog
  trips, router ejections, PS replication/promotion, armed fault-seam
  firings) plus :func:`dump_postmortem` — the one bundle writer every
  self-healing seam dumps through on a terminal event, retrieved by
  the ``postmortem`` DKT1 verb and rendered by
  ``tools/dkt_postmortem.py``.
- ``slo``: declarative :class:`SloSpec` objectives evaluated from the
  registries (:func:`evaluate_slos` / :class:`SloEvaluator`); verdicts
  (``ok``/``warn``/``breach``) ride the ``health`` verb, breaches land
  in the recorder and a registry counter, and the fleet health sweep
  can eject on sustained breach.
- ``timeseries``: :class:`MetricsHistory` — a bounded ring of
  periodic registry snapshots answering WINDOWED queries (reset-aware
  counter rates, windowed histogram quantiles, EWMA/trend) and
  multi-window burn-rate SLO verdicts (fast 1m / slow 10m); served by
  the ``timeseries`` DKT1 verb and rendered as sparkline/trend
  columns by ``tools/dkt_top.py``.
- ``compile_ledger``: :class:`CompileLedger` — every runtime XLA
  program mint recorded (key, wall seconds, warmup|serving trigger,
  in-flight requests) at the ``DecodeStepper._jit`` chokepoint, with
  compile-STORM detection (a post-warmup serving-path mint of a
  never-seen program trips an ``xla.compile.storm`` event + gauge)
  and per-request ``xla.compile`` trace spans.
- ``overlap``: :class:`OverlapLedger` — per-scheduler-iteration
  dispatch/ready/collect stamps turning the decode loop's host bubble
  (iteration wall minus device wall) into the
  ``serving_step_bubble_seconds`` histogram and the
  ``serving_overlap_efficiency`` gauge, the committed zero-bubble
  numbers ``bench_serving.py`` and ``dkt_top`` read.
"""

from distkeras_tpu.obs.compile_ledger import CompileLedger
from distkeras_tpu.obs.overlap import OverlapLedger
from distkeras_tpu.obs.recorder import (
    POSTMORTEM_SCHEMA,
    FlightRecorder,
    build_postmortem,
    dump_postmortem,
    latest_postmortem,
)
from distkeras_tpu.obs.timeseries import (
    FAST_WINDOW,
    SLOW_WINDOW,
    MetricsHistory,
    worst_burn,
)
from distkeras_tpu.obs.slo import (
    SloEvaluator,
    SloSpec,
    default_serving_slos,
    default_training_slos,
    evaluate_slos,
)
from distkeras_tpu.obs.metrics import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    MetricsRegistry,
    label_samples,
    parse_prometheus,
    render_prometheus,
)
from distkeras_tpu.obs.tracing import (
    COLLECTOR,
    Span,
    TraceCollector,
    TraceContext,
    new_id,
    request_spans,
    span_record,
    stamp_error_trace,
    start_span,
    timeline_complete,
)

__all__ = [
    "COLLECTOR",
    "FAST_WINDOW",
    "POSTMORTEM_SCHEMA",
    "SLOW_WINDOW",
    "CompileLedger",
    "Counter",
    "CounterGroup",
    "FlightRecorder",
    "MetricsHistory",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "OverlapLedger",
    "SloEvaluator",
    "SloSpec",
    "Span",
    "TraceCollector",
    "TraceContext",
    "build_postmortem",
    "default_serving_slos",
    "default_training_slos",
    "dump_postmortem",
    "evaluate_slos",
    "latest_postmortem",
    "label_samples",
    "new_id",
    "parse_prometheus",
    "render_prometheus",
    "request_spans",
    "span_record",
    "stamp_error_trace",
    "start_span",
    "timeline_complete",
    "worst_burn",
]
