"""Typed metrics registry: Prometheus-style Counter/Gauge/Histogram.

Every subsystem used to keep its own hand-rolled counter dict
(``ContinuousBatcher.counters``, ``FleetRouter.counters``,
``PrefixStore.counters``, the PS meta counters) with no shared naming,
no types, and no way to scrape them uniformly — a dashboard had to
know five ad-hoc ``stats()`` shapes. This module is the standard
answer: a process-cheap typed registry with

- :class:`Counter` — monotonic event count (hot-path ``inc`` is one
  attribute add; components already serialize increments under their
  own locks, exactly as the old dicts did);
- :class:`Gauge` — a point-in-time value, either ``set()`` by the
  owner or computed by a callback at snapshot time (queue depth,
  active slots — values that already live in the component);
- :class:`Histogram` — log-bucketed distribution (geometric bucket
  boundaries, so 60 µs..60 s of latency fits in ~20 buckets);
  ``observe`` is a bisect into a ~20-entry list plus two adds under
  the histogram's own lock (observations come from concurrent
  connection threads, unlike counter increments);
- :class:`CounterGroup` — a ``MutableMapping`` facade over a family of
  registry counters, so existing ``counters["submitted"] += 1`` call
  sites (and tests, and the bench's counter resets) keep working while
  the values become scrapeable typed metrics;
- :class:`MetricsRegistry` — the collection face: ``snapshot()``
  returns JSON-able samples (what the ``metrics`` DKT1 verb ships),
  :func:`render_prometheus` turns samples into the text exposition
  format, and :func:`parse_prometheus` is the validating reader tests
  and the bench harness use to prove the dump actually parses.

Naming convention (see docs/ARCHITECTURE.md "Observability"):
``<subsystem>_<what>[_<unit>]`` in snake_case — e.g.
``serving_scheduler_submitted``, ``serving_request_total_seconds``,
``fleet_router_forwards``. Counters get a ``_total`` suffix in the
Prometheus rendering only (the snapshot keeps the raw name). Labels
are flat string pairs; the fleet router labels every aggregated
replica sample with ``replica="host:port"``.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from collections.abc import MutableMapping


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter. ``inc`` is the hot path: one attribute add,
    no lock — callers that race increments already hold their own
    component lock (the same contract the raw dicts had)."""

    kind = "counter"
    __slots__ = ("name", "help", "labels", "value")

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.value = 0

    def inc(self, n=1) -> None:
        self.value += n

    def sample(self) -> dict:
        s = {
            "name": self.name,
            "kind": self.kind,
            "labels": dict(self.labels),
            "value": self.value,
        }
        if self.help:
            s["help"] = self.help
        return s


class Gauge:
    """Point-in-time value: ``set()`` by the owner, or computed by
    ``fn`` at snapshot time (for values that already live in the
    component — queue depth, heartbeat age — a callback gauge costs
    nothing between scrapes)."""

    kind = "gauge"
    __slots__ = ("name", "help", "labels", "value", "fn")

    def __init__(self, name: str, help: str = "", labels: dict | None = None,
                 fn=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.value = 0.0
        self.fn = fn

    def set(self, v) -> None:
        self.value = v

    def sample(self) -> dict:
        v = self.value
        if self.fn is not None:
            try:
                v = self.fn()
            except Exception:  # noqa: BLE001 — a scrape must never crash
                v = None
        if v is not None and not isinstance(v, (int, float, bool)):
            v = float(v)
        s = {
            "name": self.name,
            "kind": self.kind,
            "labels": dict(self.labels),
            "value": v,
        }
        if self.help:
            s["help"] = self.help
        return s


class Histogram:
    """Log-bucketed distribution. Bucket boundaries form a geometric
    ladder ``start * factor**i`` — the latency-histogram shape where
    relative error is constant across decades, and 60 µs..60 s fits in
    ~20 buckets. ``observe`` is a bisect into that ~20-entry list plus
    two adds, under the histogram's OWN lock: unlike counters (whose
    increments all sit under component locks already), histograms are
    observed from concurrent connection threads at request completion,
    and a request-scale lock is cheap while a torn count/bucket pair
    would make the exposition internally inconsistent."""

    kind = "histogram"
    __slots__ = (
        "name", "help", "labels", "bounds", "bucket_counts", "count",
        "sum", "_lock",
    )

    def __init__(self, name: str, help: str = "", labels: dict | None = None,
                 start: float = 1e-4, factor: float = 2.0,
                 num_buckets: int = 20):
        if start <= 0 or factor <= 1.0 or num_buckets < 1:
            raise ValueError(
                "need start > 0, factor > 1, num_buckets >= 1; got "
                f"{start}, {factor}, {num_buckets}"
            )
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.bounds = [start * factor ** i for i in range(num_buckets)]
        self.bucket_counts = [0] * (num_buckets + 1)  # +1 = overflow/+Inf
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        i = bisect_left(self.bounds, v)
        with self._lock:
            self.bucket_counts[i] += 1
            self.count += 1
            self.sum += v

    def quantile(self, q: float) -> float | None:
        """Bucket-resolution quantile estimate (upper bound of the
        bucket holding the q-th observation) — what ``dkt_top`` shows.
        None until the first observation."""
        with self._lock:
            count = self.count
            counts = list(self.bucket_counts)
        if count == 0:
            return None
        target = max(1, int(q * count))
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return (
                    self.bounds[i]
                    if i < len(self.bounds)
                    else self.bounds[-1]
                )
        return self.bounds[-1]

    def sample(self) -> dict:
        with self._lock:
            counts = list(self.bucket_counts)
            count, total = self.count, self.sum
        cum, buckets = 0, []
        for i, c in enumerate(counts):
            cum += c
            le = self.bounds[i] if i < len(self.bounds) else "+Inf"
            buckets.append([le, cum])
        s = {
            "name": self.name,
            "kind": self.kind,
            "labels": dict(self.labels),
            "count": count,
            "sum": total,
            "buckets": buckets,
        }
        if self.help:
            s["help"] = self.help
        return s


class CounterGroup(MutableMapping):
    """Dict-shaped facade over a family of counters, so the components'
    existing ``counters["key"] += 1`` hot paths (and every test /
    bench-reset call site written against the old raw dicts) keep
    working unchanged while the values become registry metrics.

    ``group[key]`` reads the counter's value, ``group[key] = v`` sets
    it (the bench zeroes counters between timed passes), ``inc(key)``
    is the explicit face. Iteration order is insertion order, like the
    dicts it replaces, so ``dict(group)`` snapshots keep their shape.
    """

    __slots__ = ("_counters",)

    def __init__(self, counters: dict[str, Counter]):
        self._counters = counters

    def __getitem__(self, key: str):
        return self._counters[key].value

    def __setitem__(self, key: str, value) -> None:
        self._counters[key].value = value

    def __delitem__(self, key: str) -> None:
        raise TypeError("CounterGroup keys are fixed at construction")

    def __iter__(self):
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)

    def inc(self, key: str, n=1) -> None:
        self._counters[key].value += n

    def counter(self, key: str) -> Counter:
        return self._counters[key]


class MetricsRegistry:
    """Process-wide (or component-owned) collection of typed metrics.

    Registration is keyed ``(name, labels)``: asking for an existing
    counter/gauge/histogram returns the live object (two call sites
    share one metric); ``group(..., fresh=True)`` REPLACES prior
    registrations instead — a rebuilt component (a supervisor-restarted
    scheduler) starts its counters at zero exactly like the dict it
    replaced, while the superseded group object keeps functioning
    standalone for anyone still holding it."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._metrics: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def _full(self, name: str) -> str:
        return f"{self.namespace}_{name}" if self.namespace else name

    def _get_or_register(self, cls, name, help, labels, fresh=False, **kw):
        name = self._full(name)
        key = (name, _label_key(labels or {}))
        with self._lock:
            m = self._metrics.get(key)
            if m is not None and not fresh:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}, not {cls.__name__}"
                    )
                return m
            m = cls(name, help=help, labels=labels, **kw)
            self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "", labels: dict | None = None,
                fresh: bool = False) -> Counter:
        return self._get_or_register(Counter, name, help, labels, fresh)

    def gauge(self, name: str, help: str = "", labels: dict | None = None,
              fn=None, fresh: bool = False) -> Gauge:
        g = self._get_or_register(Gauge, name, help, labels, fresh)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  labels: dict | None = None, fresh: bool = False,
                  **kw) -> Histogram:
        return self._get_or_register(Histogram, name, help, labels, fresh,
                                     **kw)

    def group(self, prefix: str, keys, help: str = "",
              labels: dict | None = None, fresh: bool = True) -> CounterGroup:
        """A :class:`CounterGroup` of counters named ``<prefix>_<key>``.
        ``fresh=True`` (the default) replaces prior registrations — a
        rebuilt component starts at zero like the dict it replaced."""
        return CounterGroup({
            k: self.counter(f"{prefix}_{k}", help=help, labels=labels,
                            fresh=fresh)
            for k in keys
        })

    def snapshot(self) -> list[dict]:
        """JSON-able samples of every registered metric — the payload
        of the ``metrics`` DKT1 verb."""
        with self._lock:
            metrics = list(self._metrics.values())
        return [m.sample() for m in metrics]


def label_samples(samples, **labels) -> list[dict]:
    """Copies of ``samples`` with ``labels`` merged in (existing keys
    win — a replica's own labels are not overwritten). The fleet
    router uses this to stamp ``replica="host:port"`` onto every
    sample it aggregates."""
    out = []
    for s in samples:
        s = dict(s)
        merged = dict(labels)
        merged.update(s.get("labels") or {})
        s["labels"] = merged
        out.append(s)
    return out


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    items = dict(labels or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(items.items())
    )
    return "{" + inner + "}"


def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace(
        "\n", r"\n"
    )


def _fmt_value(v) -> str:
    if v is None:
        return "NaN"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def render_prometheus(samples) -> str:
    """The Prometheus text exposition format over snapshot ``samples``
    (``# HELP``/``# TYPE`` headers once per metric name, counters
    suffixed ``_total`` per convention, histograms as cumulative
    ``_bucket`` series plus ``_sum``/``_count``). Samples are grouped
    by metric name first — the exposition format requires every line
    of a family contiguous under its ``# TYPE``, and the fleet
    aggregate arrives interleaved (router samples, then each
    replica's full snapshot); first-seen name order and intra-family
    sample order are preserved. The ``# HELP`` line renders the
    metric's registered help text when one exists (a strict scraper
    treats a family without its comment headers as a foreign line —
    the bare exposition parsed in our reader but not everywhere), and
    always before ``# TYPE`` per the format's ordering rule."""
    families: dict[str, list] = {}
    for s in samples:
        name = s["name"] + ("_total" if s["kind"] == "counter" else "")
        families.setdefault(name, []).append(s)
    lines = []
    for name, family in families.items():
        help_text = next(
            (s["help"] for s in family if s.get("help")), None
        )
        if help_text:
            lines.append(
                "# HELP " + name + " "
                + str(help_text).replace("\\", r"\\").replace(
                    "\n", r"\n"
                )
            )
        lines.append(f"# TYPE {name} {family[0]['kind']}")
        for s in family:
            _render_sample(lines, name, s)
    return "\n".join(lines) + "\n"


def _render_sample(lines, name, s) -> None:
    labels = s.get("labels") or {}
    if s["kind"] == "histogram":
        for le, cum in s["buckets"]:
            lines.append(
                f"{name}_bucket"
                f"{_fmt_labels(labels, {'le': le})} {cum}"
            )
        lines.append(f"{name}_sum{_fmt_labels(labels)} "
                     f"{_fmt_value(s['sum'])}")
        lines.append(f"{name}_count{_fmt_labels(labels)} {s['count']}")
    else:
        lines.append(
            f"{name}{_fmt_labels(labels)} {_fmt_value(s['value'])}"
        )


def parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Strict-enough validating parser of the text exposition format:
    returns ``(name, labels, value)`` triples, raising ``ValueError``
    on any malformed line. The bench harness and the schema tests use
    this to prove the dump the ``metrics`` verb serves actually
    parses — 'Prometheus-style' is a checked claim, not a vibe."""
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            labels: dict[str, str] = {}
            if series.endswith("}"):
                name, _, inner = series.partition("{")
                inner = inner[:-1]
                while inner:
                    k, _, rest = inner.partition("=")
                    if not rest.startswith('"'):
                        raise ValueError("unquoted label value")
                    v, rest = _read_quoted(rest)
                    labels[k] = v
                    inner = rest.lstrip(",")
            else:
                name = series
            if not name or not all(
                c.isalnum() or c in "_:" for c in name
            ) or name[0].isdigit():
                raise ValueError(f"bad metric name {name!r}")
            out.append((name, labels, float(value)))
        except ValueError as e:
            raise ValueError(f"line {lineno}: {line!r}: {e}") from None
    return out


def _read_quoted(s: str) -> tuple[str, str]:
    """Read a leading double-quoted string (with backslash escapes);
    returns (value, remainder-after-the-closing-quote)."""
    assert s.startswith('"')
    buf, i = [], 1
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            buf.append({"n": "\n", '"': '"', "\\": "\\"}.get(
                s[i + 1], s[i + 1]
            ))
            i += 2
            continue
        if c == '"':
            return "".join(buf), s[i + 1:]
        buf.append(c)
        i += 1
    raise ValueError("unterminated label value")
