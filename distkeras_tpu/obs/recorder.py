"""Black-box flight recorder + crash post-mortem bundles.

PR 7 made the HAPPY path legible (per-request traces, typed metrics);
this module is the failure path's memory. When the system self-heals —
a watchdog trips and rebuilds the scheduler, a blamed slot is
quarantined, a standby promotes, a fleet replica is ejected — the
evidence used to evaporate with the recovery: triage meant re-running
the soak with seeds and reading four JSONL files. The flight recorder
keeps a bounded, always-on ring of structured events per component
(the airliner black box, not a log file), and on any TERMINAL event
the owning component dumps ONE self-contained JSON bundle — ring
contents, metrics snapshot, in-flight request table with trace ids,
config knobs, armed fault-seam state — that explains the failure
after the fact without a re-run.

- :class:`FlightRecorder` — bounded thread-safe ring of event dicts
  (the ``TraceCollector`` ring discipline, applied to component
  events instead of spans). ``record(kind, **fields)`` is the hot
  path: one lock, one append to a preallocated deque — cheap enough
  to run ALWAYS ON (unlike tracing, which is opt-in per request),
  because the ring is what makes the next unexplained failure
  explainable. Overwrites (ring-bound evictions) are counted, and
  :meth:`register_gauges` exposes the ring's fill/overwrite state in
  the owning component's metrics registry.
- :func:`dump_postmortem` — THE shared bundle writer: engine
  supervisor, ``FleetRouter``, ``SocketParameterServer``, and the
  soak harnesses all dump through it, so every bundle carries the
  same schema (``POSTMORTEM_SCHEMA`` — pinned by a golden test).
- :func:`latest_postmortem` — newest bundle in a ``postmortem_dir``
  (filenames sort by time); what the ``postmortem`` DKT1 verb and
  ``tools/dkt_postmortem.py`` read back.

Event kinds in the catalogue (see docs/ARCHITECTURE.md "Post-mortem
& SLO" for the full table): ``scheduler.iteration`` /
``scheduler.blame`` / ``scheduler.quarantine`` /
``scheduler.prefill_failure``, ``engine.watchdog_trip`` /
``engine.restarted`` / ``engine.degraded``, ``router.route`` /
``router.eject`` / ``router.rejoin`` / ``router.failover`` /
``router.drain``, ``ps.commit`` / ``ps.attach`` / ``ps.detach`` /
``ps.gate_refused`` / ``ps.sync`` / ``ps.promoted`` /
``ps.stand_down``, ``fault.fired`` (armed seam firings, via
``faults.add_observer``), ``slo.breach`` / ``slo.warn``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

#: bundle schema version — bump on any breaking key change; the golden
#: test pins the key set for the current version
POSTMORTEM_SCHEMA = 1


class FlightRecorder:
    """Bounded, thread-safe, always-on ring of component events.

    One event = one flat JSON-able dict ``{"ts", "kind", ...fields}``.
    The ring keeps the most recent ``capacity`` events; what the bound
    evicted is counted in ``overwrites`` (never silent — the bundle
    and the registry gauge both report it). ``events_recorded`` is the
    lifetime total."""

    def __init__(self, capacity: int = 2048):
        self._events: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.capacity = int(capacity)
        self.events_recorded = 0
        self.overwrites = 0

    def record(self, kind: str, **fields) -> dict:
        ev = {"ts": round(time.time(), 6), "kind": kind}
        if fields:
            ev.update(fields)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.overwrites += 1
            self._events.append(ev)
            self.events_recorded += 1
        return ev

    def snapshot(self) -> list[dict]:
        """Copy of the ring, oldest first — the bundle payload."""
        with self._lock:
            return list(self._events)

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def register_gauges(self, registry, prefix: str) -> None:
        """Expose the ring's state as scrape-time gauges in the owning
        component's registry (``<prefix>_recorder_events`` lifetime
        total, ``<prefix>_recorder_overwrites`` ring-bound evictions)
        — today drops are counted but not scrapeable anywhere else."""
        registry.gauge(
            f"{prefix}_recorder_events",
            fn=lambda: self.events_recorded,
        )
        registry.gauge(
            f"{prefix}_recorder_overwrites",
            fn=lambda: self.overwrites,
        )

    # -- fault-seam observer -------------------------------------------------

    def fault_observer(self, site: str, action: str, ctx: dict) -> None:
        """``faults.add_observer`` callback: every ARMED seam firing
        lands in the ring as a ``fault.fired`` event naming the seam —
        the post-mortem's "what was injected right before this died"
        line. Context values are summarized, not embedded (an active
        mask array must not ride a JSON bundle)."""
        summary = {}
        for k, v in ctx.items():
            if isinstance(v, (str, int, float, bool)) or v is None:
                summary[k] = v
            else:
                summary[k] = repr(v)[:80]
        self.record("fault.fired", site=site, action=action, **summary)


def build_postmortem(component: str, reason: str, recorder=None,
                     metrics=None, in_flight=None, config=None,
                     trace_spans=None, slo=None, detail=None) -> dict:
    """Assemble a post-mortem bundle dict (the one schema every dump
    shares). ``metrics`` is a ``metrics_snapshot()``-style sample
    list; ``in_flight`` the owning component's request table (with
    trace ids); ``trace_spans`` any spans recovered for those trace
    ids; ``slo`` a forced SLO verdict at dump time."""
    from distkeras_tpu import faults

    return {
        "schema": POSTMORTEM_SCHEMA,
        "component": component,
        "reason": reason,
        "ts": round(time.time(), 6),
        "events": [] if recorder is None else recorder.snapshot(),
        "metrics": list(metrics or []),
        "in_flight": list(in_flight or []),
        "config": dict(config or {}),
        "fault_seams": faults.describe_active(),
        "trace_spans": list(trace_spans or []),
        "slo": slo,
        "detail": dict(detail or {}),
    }


def dump_postmortem(postmortem_dir, component: str, reason: str,
                    **kwargs):
    """Build a bundle and write it to ``postmortem_dir`` as one JSON
    file (name sorts by time, so the newest file IS the latest
    bundle). Returns ``(bundle, path)``; ``path`` is None when
    ``postmortem_dir`` is None (the bundle is still built, so the
    ``postmortem`` verb can serve it from memory). Best-effort on IO:
    a full disk must not turn a self-healing component's dump into a
    second crash — the write failure is recorded in the bundle it
    could not persist."""
    bundle = build_postmortem(component, reason, **kwargs)
    if postmortem_dir is None:
        return bundle, None
    path = os.path.join(
        postmortem_dir,
        f"postmortem_{component}_{bundle['ts']:.6f}_{os.getpid()}.json",
    )
    try:
        os.makedirs(postmortem_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=repr)
        os.replace(tmp, path)  # readers never see a half-written bundle
    except OSError as e:
        bundle["detail"]["dump_error"] = repr(e)
        return bundle, None
    return bundle, path


def _bundle_ts(name: str) -> float:
    """The dump timestamp embedded in a bundle filename
    (``postmortem_<component>_<ts>_<pid>.json``); component names may
    themselves contain underscores, so parse from the right. Unparsable
    names sort oldest."""
    try:
        return float(name[:-len(".json")].rsplit("_", 2)[1])
    except (ValueError, IndexError):
        return float("-inf")


def latest_postmortem(postmortem_dir):
    """Newest bundle in ``postmortem_dir`` as ``(bundle, path)``, or
    ``(None, None)`` when the directory holds none. Ordered by the
    timestamp IN the filename, not lexicographically — a directory
    shared by several components (engine + router) must yield the
    newest incident, not the lexicographically-last component's."""
    try:
        names = sorted(
            (
                n for n in os.listdir(postmortem_dir)
                if n.startswith("postmortem_") and n.endswith(".json")
            ),
            key=_bundle_ts,
        )
    except OSError:
        return None, None
    while names:
        path = os.path.join(postmortem_dir, names.pop())
        try:
            with open(path) as f:
                return json.load(f), path
        except (OSError, json.JSONDecodeError):
            continue  # torn/foreign file: fall back to the next-newest
    return None, None
