"""Deterministic fault injection for the serving runtime.

The recovery paths in ``serving/`` (slot blame + quarantine, the
supervisor watchdog, client retry/reconnect) are unprovable without a
way to make the underlying failures happen ON DEMAND and REPEATABLY.
This module is that switch: production code registers named injection
seams at explicit hook points (``fire("stepper.step", ...)``) and a
test arms a seeded :class:`FaultPlan` against them. Disarmed — the
default, always, in production — every seam is a module-global load
plus a ``None`` check; no locks, no allocation, no branches on state
that could drift.

Seam catalogue (the hook points that exist today)::

    scheduler.loop      engine scheduler thread, top of every iteration
    stepper.step        DecodeStepper.step, before any device work
    stepper.verify      DecodeStepper.spec_step, before the compiled
                        speculative verify (drafts already proposed)
    stepper.prefill     begin_admit / prefill_chunk, before device work
    prefix_cache.fetch  PrefixStore.lookup (engine degrades to a miss)
    kv.alloc            paging.PageAllocator.alloc, before any pool
                        state changes — an injected raise makes page
                        exhaustion / allocator failure happen on
                        demand; the scheduler surfaces an exhausted
                        admission as typed retriable ``overloaded``,
                        never a hung slot or a corrupt stream
    kv.swap             DecodeStepper.swap_out / swap_in (QoS
                        preemption), before any device work or state
                        change; ``ctx["direction"]`` is "out"/"in".
                        A failed swap-out ABORTS the preemption (the
                        victim keeps decoding untouched); a failed
                        swap-in fails only the preempted request,
                        typed — the scheduler never wedges and no
                        page or host swap state leaks
    kv.transfer         the disaggregated prefill/decode transfer hop
                        (serving/kv_transfer.py): fires in
                        ``ServingEngine.prefill`` before the finished
                        slot's state is encoded for the wire
                        (``ctx["direction"]`` "send") and in
                        ``ServingEngine.resume`` before a received
                        frame is decoded ("recv"). A send failure
                        fails only its own request, typed; a recv
                        failure replies typed to the router, which
                        retries the SAME bytes on a sibling decode
                        worker (bounded) — no direction can hang a
                        client or strand a slot
    kv.peer             the fleet KV fabric's worker-to-worker paths
                        (serving/kv_transfer.py ``PeerFabric`` and the
                        engine's ``kv.fetch`` serving half), fired
                        BEFORE any state changes; ``ctx["direction"]``
                        is "fetch" (requester about to dial a sibling
                        for prefix pages), "push" (prefill worker about
                        to push a DKTX frame point-to-point to its
                        paired decode worker), or "serve" (a sibling's
                        fetch request about to be answered). Every
                        failure direction degrades: a failed fetch
                        falls back to local recompute (token-identical
                        to the never-fetched run), a failed push
                        returns the frame to the router's relay path,
                        a failed serve replies typed — no direction
                        can hang a request or corrupt a cache
    server.dispatch     ServingServer verb dispatch (typed-reply path)
    server.reply        ServingServer before sending a reply frame
    router.dispatch     FleetRouter verb dispatch, before a replica is
                        picked — an injected typed ServingError rides
                        the normal typed-reply path to the client
    router.health       FleetRouter health poll, per replica per sweep,
                        before the replica is dialed — an injected
                        raise counts as a failed poll (enough of them
                        ejects the replica until a clean poll rejoins
                        it)
    net.send            networking.send_data (both PS and serving wire)
    net.recv            networking.recv_data
    net.delay           ServingServer data-path verbs (generate /
                        predict / prefill / kv.transfer), fired with
                        ``ctx["verb"]`` and ``ctx["port"]`` before the
                        verb runs — arm with ``action="delay"`` and a
                        ``when`` filter on the port to make ONE
                        replica slow while its health polls stay
                        green: the gray failure binary health can't
                        see, which the router's per-replica circuit
                        breakers (latency-outlier trip) must catch
    ps.pull             ParameterServer.pull, client-facing entry (both
                        the in-process and socket transports), before
                        any state is read
    ps.commit           ParameterServer.commit, client-facing entry,
                        before decompress/dedup/apply — an injected
                        raise rejects the commit wholesale, so the
                        worker's commit_id resend is the recovery path
                        (replication applies are NOT client commits and
                        do not re-fire this seam)
    ps.replicate        primary-side replication sink, before the
                        commit record is forwarded to a warm standby
                        (failure detaches the sink; the standby
                        re-syncs with a fresh snapshot attach)

Actions::

    raise     raise ``exc`` (default ``InjectedFault``) at the seam
    delay     sleep ``delay`` seconds, then continue (slow step/peer)
    drop      server.reply only: close the connection without replying
    reset     net.send only: send a partial frame, then RST the socket
    truncate  net.send only: declare the full length, send half, FIN
    corrupt   net.send only: flip a byte mid-payload, send normally

Determinism: triggering is COUNTED, not timed — ``after`` skips the
first N matching events, ``times`` bounds how often the seam fires
(``None`` = every match), ``when(ctx)`` filters on the call context
(e.g. the step's active mask). ``probability`` draws from the plan's
own seeded RNG, so even probabilistic chaos replays exactly.

Usage::

    plan = FaultPlan(seed=0)
    plan.arm("stepper.step", exc=RuntimeError("boom"))       # once
    plan.arm("net.send", action="reset", after=2)
    with plan:                      # activate / deactivate
        ...drive the engine...
    assert plan.fired("stepper.step") == 1

Only one plan is active per process at a time (the seams are global,
like the failures they stand in for); nesting raises.
"""

from __future__ import annotations

import random
import threading
import time


SITES = frozenset(
    {
        "scheduler.loop",
        "stepper.step",
        "stepper.verify",
        "stepper.prefill",
        "prefix_cache.fetch",
        "kv.alloc",
        "kv.swap",
        "kv.transfer",
        "kv.peer",
        "server.dispatch",
        "server.reply",
        "router.dispatch",
        "router.health",
        "net.send",
        "net.recv",
        "net.delay",
        "ps.pull",
        "ps.commit",
        "ps.replicate",
    }
)

ACTIONS = frozenset(
    {"raise", "delay", "drop", "reset", "truncate", "corrupt"}
)


class InjectedFault(RuntimeError):
    """Default exception raised by an armed ``raise`` seam — typed so
    tests (and the blame machinery's counters) can tell an injected
    failure from an organic one."""


_ACTIVE: "FaultPlan | None" = None
_ACTIVE_LOCK = threading.Lock()

# armed-fire observers (the flight recorder's tap): called with
# (site, action, ctx) AFTER a seam matched and BEFORE it acts, so a
# ``raise`` seam's firing is on the record before the exception that
# kills the component it hit. Observers run only on the ARMED path —
# the disarmed fast path in :func:`fire` never reads this list.
_OBSERVERS: list = []
_OBSERVERS_LOCK = threading.Lock()


def add_observer(fn) -> None:
    """Register ``fn(site, action, ctx)`` to be called on every armed
    seam firing (e.g. ``FlightRecorder.fault_observer``). Observers
    must not raise; failures are swallowed — observability must never
    change what an injected fault does."""
    with _OBSERVERS_LOCK:
        if fn not in _OBSERVERS:
            _OBSERVERS.append(fn)


def remove_observer(fn) -> None:
    with _OBSERVERS_LOCK:
        if fn in _OBSERVERS:
            _OBSERVERS.remove(fn)


def _notify(site: str, action: str, ctx: dict) -> None:
    with _OBSERVERS_LOCK:
        observers = list(_OBSERVERS)
    for fn in observers:
        try:
            fn(site, action, ctx)
        except Exception:  # noqa: BLE001 — observers are best-effort
            pass


def describe_active() -> list | None:
    """JSON-able arming state of the active plan (None when disarmed)
    — what a post-mortem bundle records so "was chaos armed, and what
    had fired" is answerable from the bundle alone."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.describe()


def fire(site: str, **ctx) -> str | None:
    """The seam. Disarmed: one global read, one ``None`` check, return.
    Armed: returns the triggered action name for caller-implemented
    behaviors (``drop``/``reset``/``truncate``/``corrupt``), handles
    ``raise`` and ``delay`` in place, returns ``None`` when no seam
    matched this event."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan._fire(site, ctx)


class _Seam:
    __slots__ = (
        "site", "action", "times", "after", "probability", "when",
        "exc", "delay", "fired",
    )

    def __init__(self, site, action, times, after, probability, when,
                 exc, delay):
        self.site = site
        self.action = action
        self.times = times  # None = unbounded
        self.after = int(after)
        self.probability = float(probability)
        self.when = when
        self.exc = exc
        self.delay = float(delay)
        self.fired = 0


class FaultPlan:
    """A seeded, countable set of armed injection seams.

    Thread-safe: seams fire from the scheduler thread, server
    connection threads, and client threads concurrently; all matching
    and bookkeeping happens under one lock (the armed path is test-only
    — the disarmed fast path in :func:`fire` never touches it)."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._seams: dict[str, list[_Seam]] = {}
        self._lock = threading.Lock()

    # -- arming -------------------------------------------------------------

    def arm(self, site: str, action: str = "raise", *, times: int | None = 1,
            after: int = 0, probability: float = 1.0, when=None,
            exc: BaseException | None = None,
            delay: float = 0.0) -> "FaultPlan":
        """Arm ``site`` with ``action``. ``times``: fires before the
        seam exhausts (``None`` = forever). ``after``: matching events
        to let pass first. ``when(ctx)``: context predicate. ``exc``:
        the exception instance a ``raise`` seam throws (default
        ``InjectedFault(site)``). Returns ``self`` for chaining."""
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}; known: "
                             f"{sorted(SITES)}")
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; known: "
                             f"{sorted(ACTIONS)}")
        if times is not None and int(times) < 1:
            raise ValueError(f"times must be >= 1 or None; got {times}")
        seam = _Seam(site, action, None if times is None else int(times),
                     after, probability, when, exc, delay)
        with self._lock:
            self._seams.setdefault(site, []).append(seam)
        return self

    # -- activation ---------------------------------------------------------

    def activate(self) -> "FaultPlan":
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is not None and _ACTIVE is not self:
                raise RuntimeError(
                    "another FaultPlan is already active; deactivate it "
                    "first (seams are process-global)"
                )
            _ACTIVE = self
        return self

    def deactivate(self) -> None:
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    def __enter__(self) -> "FaultPlan":
        return self.activate()

    def __exit__(self, *exc) -> None:
        self.deactivate()

    # -- firing -------------------------------------------------------------

    def _fire(self, site: str, ctx: dict) -> str | None:
        with self._lock:
            seam = self._match(site, ctx)
            if seam is None:
                return None
            seam.fired += 1
            action, exc, delay = seam.action, seam.exc, seam.delay
        # act OUTSIDE the lock: a delay seam must not serialize every
        # other seam behind its sleep. Observers see the firing FIRST,
        # so a raise lands in the flight recorder before it propagates.
        _notify(site, action, ctx)
        if action == "raise":
            raise exc if exc is not None else InjectedFault(
                f"injected fault at {site}"
            )
        if action == "delay":
            time.sleep(delay)
        return action

    def _match(self, site: str, ctx: dict) -> _Seam | None:
        """First armed seam for ``site`` whose gates all pass. Caller
        holds the lock."""
        for seam in self._seams.get(site, ()):
            if seam.times is not None and seam.fired >= seam.times:
                continue
            if seam.when is not None and not seam.when(ctx):
                continue
            if seam.after > 0:
                seam.after -= 1
                continue
            if seam.probability < 1.0 and (
                self._rng.random() >= seam.probability
            ):
                continue
            return seam
        return None

    # -- observability ------------------------------------------------------

    def fired(self, site: str | None = None) -> int:
        """Total fires, for one site or the whole plan."""
        with self._lock:
            seams = (
                self._seams.get(site, ())
                if site is not None
                else [s for lst in self._seams.values() for s in lst]
            )
            return sum(s.fired for s in seams)

    def describe(self) -> list:
        """JSON-able arming state: one row per armed seam with its
        gates and fire count — the ``fault_seams`` section of a
        post-mortem bundle."""
        with self._lock:
            return [
                {
                    "site": s.site,
                    "action": s.action,
                    "times": s.times,
                    "after": s.after,
                    "probability": s.probability,
                    "fired": s.fired,
                }
                for lst in self._seams.values()
                for s in lst
            ]
