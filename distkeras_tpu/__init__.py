"""distkeras_tpu — a TPU-native distributed deep-learning framework.

A from-scratch JAX/XLA rebuild of the capability surface of
``amoussoubaruch/dist-keras`` (a fork of ``cerndb/dist-keras``): data-parallel
training with a menu of synchronous and asynchronous optimization schemes
(DOWNPOUR, AEASGD, EAMSGD, ADAG, DynSGD), a parameter-server runtime, Spark-
DataFrame-style preprocessing transformers, predictors and evaluators — all
re-designed TPU-first:

- single-worker forward/backward  -> ``jax.grad`` over a jit-compiled step
  (reference: distkeras/workers.py -> Worker.train)
- socket parameter server         -> ICI ``psum`` allreduce for the sync path
  (reference: distkeras/parameter_servers.py -> SocketParameterServer) and a
  host-resident, thread/TCP-served PS for the async algorithms
- Spark mapPartitions launch      -> ``shard_map`` over a ``jax.sharding.Mesh``
  (reference: distkeras/trainers.py -> DistributedTrainer.train)
- Spark DataFrame + transformers  -> host-side columnar ``Dataset`` + the same
  transformer zoo (reference: distkeras/transformers.py)
"""

__version__ = "0.1.0"

from distkeras_tpu.trainers import (
    Trainer,
    SingleTrainer,
    EnsembleTrainer,
    AveragingTrainer,
    DistributedTrainer,
    AsynchronousDistributedTrainer,
    SynchronousDistributedTrainer,
    SequenceParallelTrainer,
    PipelineParallelTrainer,
    DOWNPOUR,
    AEASGD,
    EAMSGD,
    ADAG,
    DynSGD,
)
from distkeras_tpu.predictors import (
    BeamSearchGenerator,
    CachedSequenceGenerator,
    ModelPredictor,
    SequenceGenerator,
    SpeculativeGenerator,
)
from distkeras_tpu.evaluators import (
    AccuracyEvaluator,
    LossEvaluator,
    PerplexityEvaluator,
    RSquaredEvaluator,
)
from distkeras_tpu.faults import FaultPlan, InjectedFault
from distkeras_tpu.networking import RetryPolicy
from distkeras_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    SloSpec,
    TraceContext,
)
from distkeras_tpu.parameter_servers import (
    CommitNotAcknowledgedError,
    ParameterServerError,
    RemoteParameterServerClient,
    SocketParameterServer,
    StandbyError,
)
from distkeras_tpu.serving import (
    ServingClient,
    ServingEngine,
    ServingServer,
)
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import (
    Transformer,
    MinMaxTransformer,
    OneHotTransformer,
    DenseTransformer,
    ReshapeTransformer,
    LabelIndexTransformer,
    StandardScaleTransformer,
)
from distkeras_tpu.models.sequential import Sequential, Model
from distkeras_tpu.job_deployment import Job
from distkeras_tpu.utils.checkpoint import Checkpointer
from distkeras_tpu.utils.profiling import MetricsLogger
