"""Inference over Datasets (reference: distkeras/predictors.py ->
ModelPredictor.predict appends a prediction column via mapPartitions).

Here prediction is a jit-compiled batched forward pass; the ragged final
batch is padded to the batch size so XLA sees one static shape (one
compile). ``data_parallel=True`` is the TPU face of the reference's
all-executors mapPartitions inference: params replicate over a
``Mesh(("data",))`` and each batch shards across the chips — GSPMD runs
the same compiled forward on every device's shard.
"""

from __future__ import annotations

import jax
import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Predictor:
    def predict(self, ds: Dataset) -> Dataset:
        raise NotImplementedError


class ModelPredictor(Predictor):
    def __init__(
        self,
        model,
        features_col="features",
        output_col="prediction",
        batch_size=1024,
        data_parallel=False,
        num_workers=None,
        mesh=None,
    ):
        """``data_parallel``: shard each inference batch across the local
        devices (or an explicit ``mesh`` with a "data" axis; ``num_workers``
        limits the device count). ``batch_size`` rounds up to a multiple of
        the mesh size so every shard is equal (the pad rows are sliced off
        the output, same as the ragged-tail pad)."""
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self._in_sh = None
        if data_parallel or mesh is not None:
            from distkeras_tpu.parallel.mesh import (
                batch_sharding,
                local_devices,
                make_mesh,
                replicated_sharding,
            )

            if mesh is None:
                mesh = make_mesh(axis_names=("data",),
                                 devices=local_devices(num_workers))
            else:
                if "data" not in mesh.axis_names:
                    raise ValueError(
                        f"mesh {dict(mesh.shape)} has no 'data' axis"
                    )
                if num_workers is not None:
                    raise ValueError(
                        "num_workers conflicts with an explicit mesh — size "
                        "the mesh itself"
                    )
            n_dev = int(mesh.shape["data"])
            self.batch_size = -(-self.batch_size // n_dev) * n_dev
            self._in_sh = batch_sharding(mesh)
            self._param_sh = replicated_sharding(mesh)
        elif num_workers is not None:
            raise ValueError("num_workers requires data_parallel=True")
        self._fn = jax.jit(
            lambda p, s, x: self.model.apply(p, s, x, train=False)[0]
        )

    def predict(self, ds: Dataset) -> Dataset:
        x = ds[self.features_col]
        n = len(x)
        params, state = self.model.params, self.model.state
        if self._in_sh is not None:
            params = jax.device_put(params, self._param_sh)
            state = jax.device_put(state, self._param_sh)
        outs = []
        for i in range(0, n, self.batch_size):
            chunk = x[i : i + self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), chunk.dtype)])
            if self._in_sh is not None:
                chunk = jax.device_put(chunk, self._in_sh)
            y = np.asarray(self._fn(params, state, chunk))
            outs.append(y[: self.batch_size - pad] if pad else y)
        return ds.with_column(self.output_col, np.concatenate(outs, axis=0))
