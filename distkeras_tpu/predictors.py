"""Inference over Datasets (reference: distkeras/predictors.py ->
ModelPredictor.predict appends a prediction column via mapPartitions).

Here prediction is a jit-compiled batched forward pass; the ragged final
batch is padded to the batch size so XLA sees one static shape (one compile).
"""

from __future__ import annotations

import jax
import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Predictor:
    def predict(self, ds: Dataset) -> Dataset:
        raise NotImplementedError


class ModelPredictor(Predictor):
    def __init__(
        self,
        model,
        features_col="features",
        output_col="prediction",
        batch_size=1024,
    ):
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self._fn = jax.jit(
            lambda p, s, x: self.model.apply(p, s, x, train=False)[0]
        )

    def predict(self, ds: Dataset) -> Dataset:
        x = ds[self.features_col]
        n = len(x)
        outs = []
        for i in range(0, n, self.batch_size):
            chunk = x[i : i + self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), chunk.dtype)])
            y = np.asarray(self._fn(self.model.params, self.model.state, chunk))
            outs.append(y[: self.batch_size - pad] if pad else y)
        return ds.with_column(self.output_col, np.concatenate(outs, axis=0))
