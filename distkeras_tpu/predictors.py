"""Inference over Datasets (reference: distkeras/predictors.py ->
ModelPredictor.predict appends a prediction column via mapPartitions).

Here prediction is a jit-compiled batched forward pass; the ragged final
batch is padded to the batch size so XLA sees one static shape (one
compile). ``data_parallel=True`` is the TPU face of the reference's
all-executors mapPartitions inference: params replicate over a
``Mesh(("data",))`` and each batch shards across the chips — GSPMD runs
the same compiled forward on every device's shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Predictor:
    def predict(self, ds: Dataset) -> Dataset:
        raise NotImplementedError


class ModelPredictor(Predictor):
    def __init__(
        self,
        model,
        features_col="features",
        output_col="prediction",
        batch_size=1024,
        data_parallel=False,
        num_workers=None,
        mesh=None,
    ):
        """``data_parallel``: shard each inference batch across the local
        devices (or an explicit ``mesh`` with a "data" axis; ``num_workers``
        limits the device count). ``batch_size`` rounds up to a multiple of
        the mesh size so every shard is equal (the pad rows are sliced off
        the output, same as the ragged-tail pad)."""
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self._in_sh = None
        if data_parallel or mesh is not None:
            from distkeras_tpu.parallel.mesh import (
                batch_sharding,
                local_devices,
                make_mesh,
                replicated_sharding,
            )

            if mesh is None:
                mesh = make_mesh(axis_names=("data",),
                                 devices=local_devices(num_workers))
            else:
                if "data" not in mesh.axis_names:
                    raise ValueError(
                        f"mesh {dict(mesh.shape)} has no 'data' axis"
                    )
                if num_workers is not None:
                    raise ValueError(
                        "num_workers conflicts with an explicit mesh — size "
                        "the mesh itself"
                    )
            n_dev = int(mesh.shape["data"])
            self.batch_size = -(-self.batch_size // n_dev) * n_dev
            self._in_sh = batch_sharding(mesh)
            self._param_sh = replicated_sharding(mesh)
        elif num_workers is not None:
            raise ValueError("num_workers requires data_parallel=True")
        self._fn = jax.jit(
            lambda p, s, x: self.model.apply(p, s, x, train=False)[0]
        )

    def predict(self, ds: Dataset) -> Dataset:
        x = ds[self.features_col]
        n = len(x)
        params, state = self.model.params, self.model.state
        if self._in_sh is not None:
            params = jax.device_put(params, self._param_sh)
            state = jax.device_put(state, self._param_sh)
        outs = []
        for i in range(0, n, self.batch_size):
            chunk = x[i : i + self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), chunk.dtype)])
            if self._in_sh is not None:
                chunk = jax.device_put(chunk, self._in_sh)
            y = np.asarray(self._fn(params, state, chunk))
            outs.append(y[: self.batch_size - pad] if pad else y)
        return ds.with_column(self.output_col, np.concatenate(outs, axis=0))


class SequenceGenerator:
    """Autoregressive decoding for the causal-LM family
    (``zoo.transformer_lm``): the inference-tier counterpart of
    ``ModelPredictor`` for sequence models. No reference counterpart
    (SURVEY §5.7: no sequence models upstream).

    The whole decode is ONE compiled program: a ``lax.scan`` over the
    generated positions, each step running the model's static-shape
    forward on the fixed (B, T) context buffer and writing the next token
    in place — XLA sees one shape, compiles once per (prompt_len, steps).
    Each step recomputes the full prefix (O(T^2 d) per token); at the
    zoo's context lengths that is cheaper than threading a KV cache
    through the layer API, and the compiled scan keeps it on-device with
    zero per-token dispatch.

    ``temperature=0`` decodes greedily; otherwise tokens sample from
    ``softmax(logits / temperature)`` seeded by ``seed`` (same seed, same
    output).
    """

    def __init__(self, model, temperature=0.0, seed=0):
        self.model = model
        self.temperature = float(temperature)
        self.seed = int(seed)
        self._fns = {}  # (prompt_len, steps) -> compiled scan

    def _decode_fn(self, prompt_len, steps, temp):
        apply = self.model.apply

        def decode(params, state, ctx, key):
            def step(carry, i):
                ctx, key = carry
                logits, _ = apply(params, state, ctx, train=False)
                pos = prompt_len - 1 + i
                logit = jax.lax.dynamic_index_in_dim(
                    logits, pos, axis=1, keepdims=False
                )  # (B, V)
                if temp == 0.0:
                    tok = jnp.argmax(logit, axis=-1)
                else:
                    key, sub = jax.random.split(key)
                    tok = jax.random.categorical(sub, logit / temp, axis=-1)
                tok = tok.astype(ctx.dtype)
                ctx = ctx.at[:, pos + 1].set(tok)
                return (ctx, key), tok

            (ctx, _), _ = jax.lax.scan(
                step, (ctx, key), jnp.arange(steps)
            )
            return ctx

        return jax.jit(decode)

    def generate(self, prompts, steps):
        """``prompts``: (B, P) int tokens, one shared prompt length P.
        Returns (B, P + steps) — the prompts continued ``steps`` tokens.
        P + steps must fit the model's built sequence length."""
        prompts = np.asarray(prompts)
        if prompts.ndim != 2 or prompts.shape[1] < 1:
            raise ValueError(
                f"prompts must be (B, P) with P >= 1; got {prompts.shape}"
            )
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1; got {steps}")
        b, p = prompts.shape
        seq_len = self.model.input_shape[0]
        if p + steps > seq_len:
            raise ValueError(
                f"prompt ({p}) + steps ({steps}) exceeds the model's "
                f"sequence length ({seq_len})"
            )
        ctx = np.zeros((b, seq_len), prompts.dtype)
        ctx[:, :p] = prompts
        # temperature is baked into the compiled scan, so it keys the
        # cache — mutating gen.temperature between calls must recompile,
        # not silently reuse the old sampling mode
        key = (p, steps, self.temperature)
        if key not in self._fns:
            self._fns[key] = self._decode_fn(p, steps, self.temperature)
        out = self._fns[key](
            self.model.params,
            self.model.state,
            jnp.asarray(ctx),
            jax.random.PRNGKey(self.seed),
        )
        return np.asarray(out)[:, : p + steps]
