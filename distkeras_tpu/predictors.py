"""Inference over Datasets (reference: distkeras/predictors.py ->
ModelPredictor.predict appends a prediction column via mapPartitions).

Here prediction is a jit-compiled batched forward pass; the ragged final
batch is padded to the batch size so XLA sees one static shape (one
compile). ``data_parallel=True`` is the TPU face of the reference's
all-executors mapPartitions inference: params replicate over a
``Mesh(("data",))`` and each batch shards across the chips — GSPMD runs
the same compiled forward on every device's shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.ops.quantization import qmatmul, qshape


class Predictor:
    def predict(self, ds: Dataset) -> Dataset:
        raise NotImplementedError


class ModelPredictor(Predictor):
    def __init__(
        self,
        model,
        features_col="features",
        output_col="prediction",
        batch_size=1024,
        data_parallel=False,
        num_workers=None,
        mesh=None,
    ):
        """``data_parallel``: shard each inference batch across the local
        devices (or an explicit ``mesh`` with a "data" axis; ``num_workers``
        limits the device count). ``batch_size`` rounds up to a multiple of
        the mesh size so every shard is equal (the pad rows are sliced off
        the output, same as the ragged-tail pad)."""
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self._in_sh = None
        if data_parallel or mesh is not None:
            from distkeras_tpu.parallel.mesh import (
                batch_sharding,
                local_devices,
                make_mesh,
                replicated_sharding,
            )

            if mesh is None:
                mesh = make_mesh(axis_names=("data",),
                                 devices=local_devices(num_workers))
            else:
                if "data" not in mesh.axis_names:
                    raise ValueError(
                        f"mesh {dict(mesh.shape)} has no 'data' axis"
                    )
                if num_workers is not None:
                    raise ValueError(
                        "num_workers conflicts with an explicit mesh — size "
                        "the mesh itself"
                    )
            n_dev = int(mesh.shape["data"])
            self.batch_size = -(-self.batch_size // n_dev) * n_dev
            self._in_sh = batch_sharding(mesh)
            self._param_sh = replicated_sharding(mesh)
        elif num_workers is not None:
            raise ValueError("num_workers requires data_parallel=True")
        self._fn = jax.jit(
            lambda p, s, x: self.model.apply(p, s, x, train=False)[0]
        )

    def predict(self, ds: Dataset) -> Dataset:
        x = ds[self.features_col]
        n = len(x)
        params, state = self.model.params, self.model.state
        if self._in_sh is not None:
            params = jax.device_put(params, self._param_sh)
            state = jax.device_put(state, self._param_sh)
        outs = []
        for i in range(0, n, self.batch_size):
            chunk = x[i : i + self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), chunk.dtype)])
            if self._in_sh is not None:
                chunk = jax.device_put(chunk, self._in_sh)
            y = np.asarray(self._fn(params, state, chunk))
            outs.append(y[: self.batch_size - pad] if pad else y)
        return ds.with_column(self.output_col, np.concatenate(outs, axis=0))


class SequenceGenerator:
    """Autoregressive decoding for the causal-LM family
    (``zoo.transformer_lm``): the inference-tier counterpart of
    ``ModelPredictor`` for sequence models. No reference counterpart
    (SURVEY §5.7: no sequence models upstream).

    The whole decode is ONE compiled program: a ``lax.scan`` over the
    generated positions, each step running the model's static-shape
    forward on the fixed (B, T) context buffer and writing the next token
    in place — XLA sees one shape, compiles once per (prompt_len, steps).
    Each step recomputes the full prefix (O(T^2 d) per token); at the
    zoo's context lengths that is cheaper than threading a KV cache
    through the layer API, and the compiled scan keeps it on-device with
    zero per-token dispatch.

    ``temperature=0`` decodes greedily; otherwise tokens sample from
    ``softmax(logits / temperature)`` seeded by ``seed`` (same seed, same
    output). ``top_k`` keeps only the k highest logits per step;
    ``top_p`` keeps the smallest nucleus whose probability mass reaches
    p (both static, compiled into the scan; combinable — k first, then
    the nucleus within it).
    """

    def __init__(self, model, temperature=0.0, seed=0, top_k=None,
                 top_p=None):
        self.model = model
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.top_k = None if top_k is None else int(top_k)
        self.top_p = None if top_p is None else float(top_p)
        self._validate_sampling()
        self._fns = {}  # decode-config key -> compiled scan

    def _validate_sampling(self):
        """Re-checked at every generate(): the sampling config is mutable
        between calls (it keys the compiled-fn cache), so mutation must
        hit the same validation the constructor applies."""
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1; got {self.top_k}")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1]; got {self.top_p}")
        if (
            (self.top_k is not None or self.top_p is not None)
            and self.temperature == 0
        ):
            raise ValueError(
                "top_k/top_p filter SAMPLING; temperature=0 is greedy "
                "argmax — pass a temperature > 0"
            )

    def _filter_logits(self, logit):
        """Apply top-k / nucleus filtering to (B, V) logits (-inf out the
        excluded tokens; jax.random.categorical renormalizes). When both
        are set the nucleus runs over the renormalized top-k values
        (B, k) — no full-vocab sort on the per-token serving path."""
        sorted_desc = None
        if self.top_k is not None and self.top_k < logit.shape[-1]:
            topv = jax.lax.top_k(logit, self.top_k)[0]  # (B, k), desc
            logit = jnp.where(logit < topv[..., -1:], -jnp.inf, logit)
            sorted_desc = topv
        if self.top_p is not None and self.top_p < 1.0:
            if sorted_desc is None:
                sorted_desc = jnp.sort(logit, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep tokens while the mass BEFORE them is < p (the first
            # token is always kept)
            keep_sorted = (cum - probs) < self.top_p
            # threshold = smallest kept logit
            thresh = jnp.min(
                jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1,
                keepdims=True,
            )
            logit = jnp.where(logit < thresh, -jnp.inf, logit)
        return logit

    def _decode_fn(self, prompt_len, steps, temp):
        apply = self.model.apply

        def decode(params, state, ctx, key):
            def step(carry, i):
                ctx, key = carry
                logits, _ = apply(params, state, ctx, train=False)
                pos = prompt_len - 1 + i
                logit = jax.lax.dynamic_index_in_dim(
                    logits, pos, axis=1, keepdims=False
                )  # (B, V)
                if temp == 0.0:
                    tok = jnp.argmax(logit, axis=-1)
                else:
                    key, sub = jax.random.split(key)
                    tok = jax.random.categorical(
                        sub, self._filter_logits(logit / temp), axis=-1
                    )
                tok = tok.astype(ctx.dtype)
                ctx = ctx.at[:, pos + 1].set(tok)
                return (ctx, key), tok

            (ctx, _), _ = jax.lax.scan(
                step, (ctx, key), jnp.arange(steps)
            )
            return ctx

        return jax.jit(decode)

    def _validate_generate_args(self, prompts, steps):
        prompts = np.asarray(prompts)
        if prompts.ndim != 2 or prompts.shape[1] < 1:
            raise ValueError(
                f"prompts must be (B, P) with P >= 1; got {prompts.shape}"
            )
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1; got {steps}")
        p = prompts.shape[1]
        seq_len = self.model.input_shape[0]
        if p + steps > seq_len:
            raise ValueError(
                f"prompt ({p}) + steps ({steps}) exceeds the model's "
                f"sequence length ({seq_len})"
            )
        return prompts, steps, seq_len

    def generate(self, prompts, steps):
        """``prompts``: (B, P) int tokens, one shared prompt length P.
        Returns (B, P + steps) — the prompts continued ``steps`` tokens.
        P + steps must fit the model's built sequence length."""
        prompts, steps, seq_len = self._validate_generate_args(prompts, steps)
        self._validate_sampling()
        b, p = prompts.shape
        ctx = np.zeros((b, seq_len), prompts.dtype)
        ctx[:, :p] = prompts
        # the sampling config is baked into the compiled scan, so it keys
        # the cache — mutating gen.temperature/top_k/top_p between calls
        # must recompile, not silently reuse the old sampling mode
        key = (p, steps, self.temperature, self.top_k, self.top_p)
        if key not in self._fns:
            self._fns[key] = self._decode_fn(p, steps, self.temperature)
        out = self._fns[key](
            self.model.params,
            self.model.state,
            jnp.asarray(ctx),
            jax.random.PRNGKey(self.seed),
        )
        return np.asarray(out)[:, : p + steps]


class CachedSequenceGenerator(SequenceGenerator):
    """KV-cache decoding for ``zoo.transformer_lm``-shaped models: the
    TPU-native serving path. No reference counterpart (SURVEY §5.7).

    ``SequenceGenerator`` re-runs the full (B, T) forward per token —
    O(T^2 d) a step, fine for training-time spot checks. Decode on real
    hardware is memory-bound, so this subclass keeps each block's K/V in
    a (B, T, H, Dh) cache: the prompt prefills the caches in one
    vectorized pass, then every generated token computes ONE row of
    attention against the cache — O(T d) a step, the whole prefill+scan
    a single compiled program. Greedy output is pinned equal to the
    uncached generator's.

    Supports the LM family's exact layer shape (Embedding -> causal
    TransformerBlock xN -> LayerNorm -> Dense); anything else (MoE
    blocks, attention hooks) raises rather than decoding incorrectly.
    """

    def __init__(self, model, temperature=0.0, seed=0, top_k=None,
                 top_p=None, kv_dtype=None):
        """``kv_dtype``: cache dtype; None keeps f32 (greedy output pinned
        bit-equal to the uncached generator). ``jnp.bfloat16`` halves the
        per-token cache-read bytes — the other big HBM stream of the
        serving path next to the int8 weights (ops/quantization.py);
        attention still accumulates in f32 (mixed-dtype einsum promotes)."""
        super().__init__(model, temperature=temperature, seed=seed,
                         top_k=top_k, top_p=top_p)
        self.kv_dtype = jnp.float32 if kv_dtype is None else kv_dtype
        from distkeras_tpu.models.layers import (
            Dense,
            Embedding,
            LayerNorm,
            TransformerBlock,
        )

        layers = list(model.layers)
        ok = (
            len(layers) >= 4
            and isinstance(layers[0], Embedding)
            and all(isinstance(l, TransformerBlock) for l in layers[1:-2])
            and isinstance(layers[-2], LayerNorm)
            and isinstance(layers[-1], Dense)
            and all(l.causal for l in layers[1:-2])
        )
        if not ok:
            raise ValueError(
                "CachedSequenceGenerator supports Embedding -> causal "
                "TransformerBlock xN (N >= 1) -> LayerNorm -> Dense models "
                f"(zoo.transformer_lm); got {[type(l).__name__ for l in layers]}"
            )
        head_shapes = {
            (l.mhsa.num_heads, l.mhsa.head_dim) for l in layers[1:-2]
        }
        if len(head_shapes) != 1:
            raise ValueError(
                "cached decode derives its cache shape from the first "
                f"block; blocks must share (num_heads, head_dim), got "
                f"{sorted(head_shapes)}"
            )
        for blk in layers[1:-2]:
            if blk.mhsa.attention_fn is not None:
                raise ValueError(
                    "cached decode computes attention itself; detach the "
                    "attention_fn hook (flash/ring) before decoding"
                )
        self._emb = layers[0]
        self._blocks = layers[1:-2]
        self._final_ln = layers[-2]
        self._head = layers[-1]

    def _block_decode(self, blk, p, x, cache_k, cache_v, pos, t_mask):
        """One token through one block against its cache. x: (B, d);
        caches: (B, T, H, Dh); t_mask: (T,) bool, True for t <= pos."""
        mh = p["mhsa"]
        h_, _ = blk.ln1.apply(p["ln1"], {}, x)
        bsz = x.shape[0]
        nh = blk.mhsa.num_heads
        hd = qshape(mh["wq"])[1] // nh
        q = qmatmul(h_, mh["wq"]).reshape(bsz, nh, hd)
        k_new = qmatmul(h_, mh["wk"]).reshape(bsz, nh, hd)
        v_new = qmatmul(h_, mh["wv"]).reshape(bsz, nh, hd)
        cache_k = jax.lax.dynamic_update_slice_in_dim(
            cache_k, k_new[:, None].astype(cache_k.dtype), pos, axis=1
        )
        cache_v = jax.lax.dynamic_update_slice_in_dim(
            cache_v, v_new[:, None].astype(cache_v.dtype), pos, axis=1
        )
        scores = jnp.einsum("bhd,bthd->bht", q, cache_k) / np.sqrt(hd)
        scores = jnp.where(t_mask[None, None, :], scores, -jnp.inf)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bht,bthd->bhd", w, cache_v).reshape(bsz, nh * hd)
        o = qmatmul(o, mh["wo"])
        if "bo" in mh:
            o = o + mh["bo"]
        x = x + o
        h_, _ = blk.ln2.apply(p["ln2"], {}, x)
        h_, _ = blk._fc1.apply(p["fc1"], {}, h_)
        h_, _ = blk._fc2.apply(p["fc2"], {}, h_)
        return x + h_, cache_k, cache_v

    def _decode_fn(self, prompt_len, steps, temp):
        from distkeras_tpu.parallel.ring_attention import dense_attention

        blocks = self._blocks
        final_ln, head = self._final_ln, self._head
        seq_len = self.model.input_shape[0]
        n_blocks = len(blocks)

        def decode(params, state, ctx, key):
            del state  # the LM family carries no mutable state
            bp = [params[str(1 + i)] for i in range(n_blocks)]
            p_emb = params["0"]
            p_ln = params[str(1 + n_blocks)]
            p_head = params[str(2 + n_blocks)]
            bsz = ctx.shape[0]
            nh = blocks[0].mhsa.num_heads
            hd = qshape(bp[0]["mhsa"]["wq"])[1] // nh

            def embed(tok, pos):
                x = p_emb["tokens"][tok]
                if "positions" in p_emb:
                    x = x + p_emb["positions"][pos]
                return x

            kvd = self.kv_dtype
            caches = [
                (
                    jnp.zeros((bsz, seq_len, nh, hd), kvd),
                    jnp.zeros((bsz, seq_len, nh, hd), kvd),
                )
                for _ in range(n_blocks)
            ]
            # ---- prefill positions 0..P-2 in one vectorized pass -------
            if prompt_len > 1:
                pp = prompt_len - 1
                x = p_emb["tokens"][ctx[:, :pp]]
                if "positions" in p_emb:
                    x = x + p_emb["positions"][:pp]
                new_caches = []
                for blk, p, (ck, cv) in zip(blocks, bp, caches):
                    mh = p["mhsa"]
                    h_, _ = blk.ln1.apply(p["ln1"], {}, x)
                    q = qmatmul(h_, mh["wq"]).reshape(bsz, pp, nh, hd)
                    k = qmatmul(h_, mh["wk"]).reshape(bsz, pp, nh, hd)
                    v = qmatmul(h_, mh["wv"]).reshape(bsz, pp, nh, hd)
                    ck = ck.at[:, :pp].set(k.astype(ck.dtype))
                    cv = cv.at[:, :pp].set(v.astype(cv.dtype))
                    o = dense_attention(q, k, v, causal=True)
                    o = qmatmul(o.reshape(bsz, pp, nh * hd), mh["wo"])
                    if "bo" in mh:
                        o = o + mh["bo"]
                    x = x + o
                    h_, _ = blk.ln2.apply(p["ln2"], {}, x)
                    h_, _ = blk._fc1.apply(p["fc1"], {}, h_)
                    h_, _ = blk._fc2.apply(p["fc2"], {}, h_)
                    x = x + h_
                    new_caches.append((ck, cv))
                caches = new_caches

            # ---- scan: one cached-attention row per generated token ----
            def step(carry, i):
                tok, caches, key = carry
                pos = prompt_len - 1 + i
                x = embed(tok, pos)
                t_mask = jnp.arange(seq_len) <= pos
                new_caches = []
                for blk, p, (ck, cv) in zip(blocks, bp, caches):
                    x, ck, cv = self._block_decode(
                        blk, p, x, ck, cv, pos, t_mask
                    )
                    new_caches.append((ck, cv))
                x, _ = final_ln.apply(p_ln, {}, x)
                logit, _ = head.apply(p_head, {}, x)  # (B, V)
                if temp == 0.0:
                    nxt = jnp.argmax(logit, axis=-1)
                else:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(
                        sub, self._filter_logits(logit / temp), axis=-1
                    )
                return (nxt.astype(tok.dtype), new_caches, key), nxt

            tok0 = ctx[:, prompt_len - 1]
            (_, _, _), toks = jax.lax.scan(
                step, (tok0, caches, key), jnp.arange(steps)
            )
            # toks: (steps, B) generated tokens for positions P..P+steps-1
            out = ctx
            out = jax.lax.dynamic_update_slice_in_dim(
                out, jnp.swapaxes(toks, 0, 1).astype(ctx.dtype),
                prompt_len, axis=1,
            )
            return out

        return jax.jit(decode)
