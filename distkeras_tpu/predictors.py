"""Inference over Datasets (reference: distkeras/predictors.py ->
ModelPredictor.predict appends a prediction column via mapPartitions).

Here prediction is a jit-compiled batched forward pass; the ragged final
batch is padded to the batch size so XLA sees one static shape (one
compile). ``data_parallel=True`` is the TPU face of the reference's
all-executors mapPartitions inference: params replicate over a
``Mesh(("data",))`` and each batch shards across the chips — GSPMD runs
the same compiled forward on every device's shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.ops.quantization import qmatmul, qshape


class Predictor:
    def predict(self, ds: Dataset) -> Dataset:
        raise NotImplementedError


class ModelPredictor(Predictor):
    def __init__(
        self,
        model,
        features_col="features",
        output_col="prediction",
        batch_size=1024,
        data_parallel=False,
        num_workers=None,
        mesh=None,
    ):
        """``data_parallel``: shard each inference batch across the local
        devices (or an explicit ``mesh`` with a "data" axis; ``num_workers``
        limits the device count). ``batch_size`` rounds up to a multiple of
        the mesh size so every shard is equal (the pad rows are sliced off
        the output, same as the ragged-tail pad)."""
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self._in_sh = None
        if data_parallel or mesh is not None:
            from distkeras_tpu.parallel.mesh import (
                batch_sharding,
                local_devices,
                make_mesh,
                replicated_sharding,
            )

            if mesh is None:
                mesh = make_mesh(axis_names=("data",),
                                 devices=local_devices(num_workers))
            else:
                if "data" not in mesh.axis_names:
                    raise ValueError(
                        f"mesh {dict(mesh.shape)} has no 'data' axis"
                    )
                if num_workers is not None:
                    raise ValueError(
                        "num_workers conflicts with an explicit mesh — size "
                        "the mesh itself"
                    )
            n_dev = int(mesh.shape["data"])
            self.batch_size = -(-self.batch_size // n_dev) * n_dev
            self._in_sh = batch_sharding(mesh)
            self._param_sh = replicated_sharding(mesh)
        elif num_workers is not None:
            raise ValueError("num_workers requires data_parallel=True")
        self._fn = jax.jit(
            lambda p, s, x: self.model.apply(p, s, x, train=False)[0]
        )

    def predict(self, ds: Dataset) -> Dataset:
        x = ds[self.features_col]
        n = len(x)
        params, state = self.model.params, self.model.state
        if self._in_sh is not None:
            params = jax.device_put(params, self._param_sh)
            state = jax.device_put(state, self._param_sh)
        outs = []
        for i in range(0, n, self.batch_size):
            chunk = x[i : i + self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), chunk.dtype)])
            if self._in_sh is not None:
                chunk = jax.device_put(chunk, self._in_sh)
            y = np.asarray(self._fn(params, state, chunk))
            outs.append(y[: self.batch_size - pad] if pad else y)
        return ds.with_column(self.output_col, np.concatenate(outs, axis=0))


class SequenceGenerator:
    """Autoregressive decoding for the causal-LM family
    (``zoo.transformer_lm``): the inference-tier counterpart of
    ``ModelPredictor`` for sequence models. No reference counterpart
    (SURVEY §5.7: no sequence models upstream).

    The whole decode is ONE compiled program: a ``lax.scan`` over the
    generated positions, each step running the model's static-shape
    forward on the fixed (B, T) context buffer and writing the next token
    in place — XLA sees one shape, compiles once per (prompt_len, steps).
    Each step recomputes the full prefix (O(T^2 d) per token); at the
    zoo's context lengths that is cheaper than threading a KV cache
    through the layer API, and the compiled scan keeps it on-device with
    zero per-token dispatch.

    ``temperature=0`` decodes greedily; otherwise tokens sample from
    ``softmax(logits / temperature)`` seeded by ``seed`` (same seed, same
    output). ``top_k`` keeps only the k highest logits per step;
    ``top_p`` keeps the smallest nucleus whose probability mass reaches
    p (both static, compiled into the scan; combinable — k first, then
    the nucleus within it).

    Sampling RNG is COUNTER-BASED (``serving.sampling``): each row's
    draw at its e-th generated token keys on ``(seed, e)`` — a pure
    function of the request, independent of batch composition, scan
    bucketing, and neighbours. This makes solo sampled decode the
    identity reference for the serving tier's per-request sampled
    decode (same seed => same tokens), exactly as solo greedy decode
    anchors the serving greedy pins.
    """

    def __init__(self, model, temperature=0.0, seed=0, top_k=None,
                 top_p=None):
        self.model = model
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.top_k = None if top_k is None else int(top_k)
        self.top_p = None if top_p is None else float(top_p)
        self._validate_sampling()
        self._fns = {}  # decode-config key -> compiled scan

    def _validate_sampling(self):
        """Re-checked at every generate(): the sampling config is mutable
        between calls (it keys the compiled-fn cache), so mutation must
        hit the same validation the constructor applies."""
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1; got {self.top_k}")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1]; got {self.top_p}")
        if (
            (self.top_k is not None or self.top_p is not None)
            and self.temperature == 0
        ):
            raise ValueError(
                "top_k/top_p filter SAMPLING; temperature=0 is greedy "
                "argmax — pass a temperature > 0"
            )

    def _validate_generate_args(self, prompts, steps):
        prompts = np.asarray(prompts)
        if prompts.ndim != 2 or prompts.shape[1] < 1:
            raise ValueError(
                f"prompts must be (B, P) with P >= 1; got {prompts.shape}"
            )
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1; got {steps}")
        p = prompts.shape[1]
        seq_len = self.model.input_shape[0]
        if p + steps > seq_len:
            raise ValueError(
                f"prompt ({p}) + steps ({steps}) exceeds the model's "
                f"sequence length ({seq_len})"
            )
        return prompts, steps, seq_len

    def generate(self, prompts, steps, eos_id=None):
        """Continue each prompt by up to ``steps`` tokens.

        ``prompts``: either a (B, P) int array (one shared prompt length)
        or a list/tuple of 1-D int sequences of DIFFERENT lengths (a
        ragged serving batch). max prompt length + steps must fit the
        model's built sequence length.

        ``eos_id``: optional end-of-sequence token id. Generation still
        runs the full compiled scan — XLA wants one static shape, so
        "early exit" is a host-side trim, not a dynamic abort — and each
        returned row is cut after its first generated ``eos_id``
        (inclusive). The wasted tail compute is the price of a single
        compiled program; at serving batch sizes it is cheaper than a
        recompile per exit position.

        Greedy decode of a ragged row is pinned equal to its solo
        rectangular call. SAMPLED rows are deterministic under a fixed
        seed AND batch-composition-independent: each row's e-th
        generated token draws from a counter-based key ``(seed, e)``
        (``serving.sampling``), so a row samples the same tokens next
        to any neighbours, at any bucketing, and alone.

        Returns a (B, P + steps) array for rectangular prompts without
        ``eos_id`` (every row the same length); otherwise a list of B 1-D
        arrays, row i being prompt i followed by its generated tokens.
        """
        self._validate_sampling()
        ragged = isinstance(prompts, (list, tuple)) and len(
            {len(np.atleast_1d(p)) for p in prompts}
        ) > 1
        if not ragged and not isinstance(prompts, np.ndarray):
            prompts = np.asarray(prompts)
        if ragged:
            return self._generate_ragged(prompts, steps, eos_id)
        prompts, steps, seq_len = self._validate_generate_args(prompts, steps)
        b, p = prompts.shape
        ctx = np.zeros((b, seq_len), prompts.dtype)
        ctx[:, :p] = prompts
        # rectangular IS the uniform-lens ragged decode: the keep-prompt/
        # frozen masks are constant-false and the RNG schedule (one split
        # per scanned position) is identical, so one builder serves both
        # (no length bucketing here — a single shared length can't churn
        # compositions, and exact start preserves the pinned rectangular
        # sampling schedule)
        out = self._run_decode(
            ctx, np.full((b,), p, np.int32), p, steps, steps
        )
        out = out[:, : p + steps]
        if eos_id is None:
            return out
        return [self._trim_eos(row, p, int(eos_id)) for row in out]

    def _run_decode(self, ctx, lens, start, n_scan, steps):
        """Compile (cached) and run the decode scan for a batch padded
        into ``ctx``: scanned positions start-1 .. start+n_scan-2. The
        sampling config is baked into the compiled scan, so it keys the
        cache — mutating gen.temperature/top_k/top_p between calls must
        recompile, not silently reuse the old sampling mode."""
        key = (
            start, n_scan, steps,
            self.temperature, self.top_k, self.top_p,
        )
        if key not in self._fns:
            self._fns[key] = self._decode_fn(
                start, n_scan, steps, self.temperature
            )
        return np.asarray(
            self._fns[key](
                self.model.params,
                self.model.state,
                jnp.asarray(ctx),
                jnp.asarray(lens),
                jax.random.PRNGKey(self.seed),
            )
        )

    @staticmethod
    def _trim_eos(row, prompt_len, eos_id):
        """Cut a decoded row after its first GENERATED eos (inclusive);
        eos tokens inside the prompt don't end the sequence."""
        gen = row[prompt_len:]
        hits = np.flatnonzero(gen == eos_id)
        if hits.size:
            return row[: prompt_len + hits[0] + 1]
        return row

    def _generate_ragged(self, prompts, steps, eos_id):
        rows = [np.atleast_1d(np.asarray(p)) for p in prompts]
        if any(r.ndim != 1 or r.shape[0] < 1 for r in rows):
            raise ValueError(
                "ragged prompts must be non-empty 1-D token sequences"
            )
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1; got {steps}")
        lens = np.asarray([r.shape[0] for r in rows], np.int32)
        min_len, max_len = int(lens.min()), int(lens.max())
        seq_len = self.model.input_shape[0]
        if max_len + steps > seq_len:
            raise ValueError(
                f"longest prompt ({max_len}) + steps ({steps}) exceeds "
                f"the model's sequence length ({seq_len})"
            )
        dtype = np.result_type(*[r.dtype for r in rows])
        ctx = np.zeros((len(rows), seq_len), dtype)
        for i, r in enumerate(rows):
            ctx[i, : lens[i]] = r
        # Bucket the compiled-program key: exact (min_len, max_len) would
        # compile per length COMPOSITION (O(L^2) programs for a serving
        # workload with naturally varying prompts). The masks are already
        # correct for any scan start <= min(lens), so round the start
        # down to a power of two and the scan length up to one, clamped
        # so the last write lands at seq_len-1 (coverage holds: the
        # validation above guarantees max_len + steps <= seq_len).
        # Greedy AND sampled output are invariant to the bucket: draws
        # key on each row's own (seed, emitted-index) counter.
        start = 1 << (min_len.bit_length() - 1)
        need = max_len - start + steps
        n_scan = min(1 << (need - 1).bit_length(), seq_len - start)
        out = self._run_decode(ctx, lens, start, n_scan, steps)
        res = [out[i, : lens[i] + steps] for i in range(len(rows))]
        if eos_id is not None:
            res = [
                self._trim_eos(row, int(L), int(eos_id))
                for row, L in zip(res, lens)
            ]
        return res

    def _decode_fn(self, min_len, n_scan, steps, temp):
        """Build THE decode scan (rectangular batches are the uniform-
        lens special case). At scanned position pos, rows still inside
        their prompt keep the prompt token (the sampled candidate is
        discarded), rows past their generation window freeze, everyone
        else appends the sampled/greedy token. Each row thus generates
        exactly ``steps`` tokens starting at its own prompt end."""
        apply = self.model.apply

        def decode(params, state, ctx, lens, key):
            del key  # RNG is counter-based: (seed, per-row emitted idx)
            temps, topk, topp, seeds = self._sampling_rows(ctx.shape[0])

            def step(carry, i):
                ctx = carry
                logits, _ = apply(params, state, ctx, train=False)
                pos = min_len - 1 + i
                logit = jax.lax.dynamic_index_in_dim(
                    logits, pos, axis=1, keepdims=False
                )  # (B, V)
                if temp == 0.0:
                    tok = jnp.argmax(logit, axis=-1)
                else:
                    from distkeras_tpu.serving import sampling as _sp

                    epos = jnp.maximum(pos + 1 - lens, 0)  # emitted idx
                    tok = _sp.sample_tokens(
                        logit, temps, topk, topp, seeds, epos
                    )
                ctx, tok = self._masked_write(ctx, lens, steps, pos, tok)
                return ctx, tok

            ctx, _ = jax.lax.scan(step, ctx, jnp.arange(n_scan))
            return ctx

        return jax.jit(decode)

    def _sampling_rows(self, b):
        """Trace-time per-row sampling params (uniform: one config per
        generator) in the vectorized shape ``serving.sampling`` takes —
        THE bridge that makes this solo path and the served per-slot
        path the same computation."""
        return (
            jnp.full((b,), self.temperature, jnp.float32),
            jnp.full((b,), 0 if self.top_k is None else self.top_k,
                     jnp.int32),
            jnp.full((b,), 1.0 if self.top_p is None else self.top_p,
                     jnp.float32),
            jnp.full((b,), self.seed, jnp.int32),
        )

    @staticmethod
    def _masked_write(ctx, lens, steps, pos, tok):
        """Write ``tok`` at column pos+1 under the ragged masks — rows
        still inside their prompt keep the prompt token (the candidate
        is discarded), rows past their generation window freeze (the
        existing pad is written back). The one place the ragged-decode
        invariant lives; both scan bodies call it. Returns (ctx, the
        token actually written)."""
        tok = tok.astype(ctx.dtype)
        cur = jax.lax.dynamic_index_in_dim(
            ctx, pos + 1, axis=1, keepdims=False
        )  # (B,) existing token (prompt or pad)
        in_prompt = (pos + 1) < lens
        frozen = (pos + 1) >= lens + steps
        tok = jnp.where(in_prompt | frozen, cur, tok)
        ctx = jax.lax.dynamic_update_slice_in_dim(
            ctx, tok[:, None], pos + 1, axis=1
        )
        return ctx, tok


class CachedSequenceGenerator(SequenceGenerator):
    """KV-cache decoding for ``zoo.transformer_lm``-shaped models: the
    TPU-native serving path. No reference counterpart (SURVEY §5.7).

    ``SequenceGenerator`` re-runs the full (B, T) forward per token —
    O(T^2 d) a step, fine for training-time spot checks. Decode on real
    hardware is memory-bound, so this subclass keeps each block's K/V in
    a (B, T, H, Dh) cache: the prompt prefills the caches in one
    vectorized pass, then every generated token computes ONE row of
    attention against the cache — O(T d) a step, the whole prefill+scan
    a single compiled program. For DENSE LMs, greedy output is pinned
    equal to the uncached generator's (bit-equal at the default f32
    caches); MoE models are exempt from that pin — see below, the
    uncached path's capacity drops are the part being deliberately not
    reproduced.

    This generator is also THE identity reference for the online
    serving tier: every ``serving.engine.DecodeStepper`` admission
    path — dense or block-PAGED (gather-based attention over a page
    pool), fresh / chunked / prefix-cache-hit / CoW-forked alike — is
    pinned token-identical to this class's solo greedy decode by the
    serving test suite and the committed bench artifacts.

    Supports the LM family's layer shapes: Embedding -> causal
    TransformerBlock xN -> LayerNorm -> Dense (``zoo.transformer_lm``),
    with an optional switch-``MoE`` layer after any block
    (``zoo.moe_transformer_lm``); anything else (attention hooks,
    non-causal blocks) raises rather than decoding incorrectly.

    MoE decoding routes WITHOUT capacity drops (``_moe_nodrop``): the
    capacity budget is a training-throughput device, and the uncached
    full-(B, T) forward even lets context PAD tokens consume it —
    serving wants each real token's true top-1 expert output. The cost
    is computing all E experts and selecting — E x the FFN FLOPs, paid
    per token at decode (tiny) AND over the whole (B, PP) prompt at
    prefill (real; at the zoo family's shapes it is still small, and
    the alternatives lose: gathering per-token expert weights
    materializes (S, D, H) copies — worse than the (E, S, H) hidden
    whenever D > E — and capacity-style dispatch reintroduces the drops
    this path exists to avoid). The win is output that does not depend
    on padding or batch composition.
    """

    def __init__(self, model, temperature=0.0, seed=0, top_k=None,
                 top_p=None, kv_dtype=None):
        """``kv_dtype``: cache dtype; None keeps f32 (greedy output pinned
        bit-equal to the uncached generator). ``jnp.bfloat16`` halves the
        per-token cache-read bytes — the other big HBM stream of the
        serving path next to the int8 weights (ops/quantization.py);
        attention still accumulates in f32 (mixed-dtype einsum promotes)."""
        super().__init__(model, temperature=temperature, seed=seed,
                         top_k=top_k, top_p=top_p)
        self.kv_dtype = jnp.float32 if kv_dtype is None else kv_dtype
        from distkeras_tpu.models.layers import (
            Dense,
            Embedding,
            LayerNorm,
            TransformerBlock,
        )
        from distkeras_tpu.parallel.expert_parallel import MoE

        layers = list(model.layers)
        shape_err = ValueError(
            "CachedSequenceGenerator supports Embedding -> causal "
            "TransformerBlock xN (each optionally followed by a MoE "
            "layer) -> LayerNorm -> Dense models (zoo.transformer_lm / "
            f"zoo.moe_transformer_lm); got "
            f"{[type(l).__name__ for l in layers]}"
        )
        if not (
            len(layers) >= 4
            and isinstance(layers[0], Embedding)
            and isinstance(layers[-2], LayerNorm)
            and isinstance(layers[-1], Dense)
        ):
            raise shape_err
        # parse the middle into (block, optional MoE) stages, keeping
        # each layer's position — param groups are keyed by layer index
        stages = []  # [(block, block_idx, moe_or_None, moe_idx_or_None)]
        i, mid_end = 1, len(layers) - 2
        while i < mid_end:
            blk = layers[i]
            if not isinstance(blk, TransformerBlock):
                raise shape_err
            moe, moe_idx = None, None
            if i + 1 < mid_end and isinstance(layers[i + 1], MoE):
                moe, moe_idx = layers[i + 1], i + 1
            stages.append((blk, i, moe, moe_idx))
            i += 1 if moe is None else 2
        if not stages:
            raise shape_err
        blocks = [s[0] for s in stages]
        if not all(b.causal for b in blocks):
            raise shape_err
        head_shapes = {(b.mhsa.num_heads, b.mhsa.head_dim) for b in blocks}
        if len(head_shapes) != 1:
            raise ValueError(
                "cached decode derives its cache shape from the first "
                f"block; blocks must share (num_heads, head_dim), got "
                f"{sorted(head_shapes)}"
            )
        for blk in blocks:
            if blk.mhsa.attention_fn is not None:
                raise ValueError(
                    "cached decode computes attention itself; detach the "
                    "attention_fn hook (flash/ring) before decoding"
                )
        self._emb = layers[0]
        self._stages = stages
        self._blocks = blocks
        self._final_ln = layers[-2]
        self._head = layers[-1]

    def _stage_chunk(self, blk, moe, p, pm, x, cache_k, cache_v, pos,
                     qmask):
        """A C-token chunk through one (block, optional MoE) stage
        against its cache — THE per-stage transformer body; single-token
        decode is the C=1 case and the speculative verify passes C=k+1.
        x: (B, C, d); caches: (B, T, H, Dh); pos: the chunk's first
        position (K/V write offset); qmask: (C, T) bool, True where
        chunk row c may attend cache position t."""
        mh = p["mhsa"]
        b, c, _ = x.shape
        nh = blk.mhsa.num_heads
        hd = qshape(mh["wq"])[1] // nh
        h_, _ = blk.ln1.apply(p["ln1"], {}, x)
        q = qmatmul(h_, mh["wq"]).reshape(b, c, nh, hd)
        k_new = qmatmul(h_, mh["wk"]).reshape(b, c, nh, hd)
        v_new = qmatmul(h_, mh["wv"]).reshape(b, c, nh, hd)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, k_new.astype(cache_k.dtype), (0, pos, 0, 0)
        )
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, v_new.astype(cache_v.dtype), (0, pos, 0, 0)
        )
        scores = jnp.einsum("bchd,bthd->bhct", q, cache_k) / np.sqrt(hd)
        scores = jnp.where(qmask[None, None], scores, -jnp.inf)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhct,bthd->bchd", w, cache_v).reshape(
            b, c, nh * hd
        )
        o = qmatmul(o, mh["wo"])
        if "bo" in mh:
            o = o + mh["bo"]
        x = x + o
        h_, _ = blk.ln2.apply(p["ln2"], {}, x)
        h_, _ = blk._fc1.apply(p["fc1"], {}, h_)
        h_, _ = blk._fc2.apply(p["fc2"], {}, h_)
        x = x + h_
        if moe is not None:
            x = x + self._moe_nodrop(pm, x)
        return x, cache_k, cache_v

    def _prefill(self, bp, caches, x):
        """Run ``x`` (B, PP, d) pre-embedded prompt prefix through every
        stage, filling each cache's first PP rows; returns (hidden,
        caches). MoE stages use the same no-drop routing as the decode
        steps, so prefill and per-token outputs agree."""
        from distkeras_tpu.parallel.ring_attention import dense_attention

        bsz, pp, _ = x.shape
        nh = self._blocks[0].mhsa.num_heads
        new_caches = []
        for (blk, _, moe, _), (p, pm), (ck, cv) in zip(
            self._stages, bp, caches
        ):
            mh = p["mhsa"]
            hd = qshape(mh["wq"])[1] // nh
            h_, _ = blk.ln1.apply(p["ln1"], {}, x)
            q = qmatmul(h_, mh["wq"]).reshape(bsz, pp, nh, hd)
            k = qmatmul(h_, mh["wk"]).reshape(bsz, pp, nh, hd)
            v = qmatmul(h_, mh["wv"]).reshape(bsz, pp, nh, hd)
            ck = ck.at[:, :pp].set(k.astype(ck.dtype))
            cv = cv.at[:, :pp].set(v.astype(cv.dtype))
            o = dense_attention(q, k, v, causal=True)
            o = qmatmul(o.reshape(bsz, pp, nh * hd), mh["wo"])
            if "bo" in mh:
                o = o + mh["bo"]
            x = x + o
            h_, _ = blk.ln2.apply(p["ln2"], {}, x)
            h_, _ = blk._fc1.apply(p["fc1"], {}, h_)
            h_, _ = blk._fc2.apply(p["fc2"], {}, h_)
            x = x + h_
            if moe is not None:
                x = x + self._moe_nodrop(pm, x)
            new_caches.append((ck, cv))
        return x, new_caches

    def _decode_prologue(self, params, ctx, prompt_len, cache_len=None):
        """Shared trace-time prologue of every cached decode builder:
        unpack the per-layer param groups (one (block, optional-MoE)
        pair per stage, keyed by layer index), build the embed closure,
        allocate the per-stage K/V caches, and prefill positions
        0..prompt_len-2. One copy — beam search, greedy/ragged decode,
        and speculative decode must never drift on cache layout or
        param indexing. ``cache_len`` overrides the cache time axis
        (speculative decode pads it so overrun chunk writes land in
        masked scratch). The embed closure clamps positions to the
        table — a no-op for every kept token; only speculative's
        discarded overrun drafts ever exceed it."""
        n_layers = len(self.model.layers)
        if cache_len is None:
            cache_len = self.model.input_shape[0]
        bp = [
            (params[str(bi)], None if mi is None else params[str(mi)])
            for (_, bi, _, mi) in self._stages
        ]
        p_emb = params["0"]
        p_ln = params[str(n_layers - 2)]
        p_head = params[str(n_layers - 1)]
        bsz = ctx.shape[0]
        nh = self._blocks[0].mhsa.num_heads
        hd = qshape(bp[0][0]["mhsa"]["wq"])[1] // nh
        n_pos = (
            p_emb["positions"].shape[0] if "positions" in p_emb else None
        )

        def embed(tok, pos):
            x = p_emb["tokens"][tok]
            if n_pos is not None:
                x = x + p_emb["positions"][jnp.minimum(pos, n_pos - 1)]
            return x

        caches = [
            (
                jnp.zeros((bsz, cache_len, nh, hd), self.kv_dtype),
                jnp.zeros((bsz, cache_len, nh, hd), self.kv_dtype),
            )
            for _ in self._stages
        ]
        if prompt_len > 1:
            pp = prompt_len - 1
            x = p_emb["tokens"][ctx[:, :pp]]
            if "positions" in p_emb:
                x = x + p_emb["positions"][:pp]
            _, caches = self._prefill(bp, caches, x)
        return bp, p_ln, p_head, embed, caches

    @staticmethod
    def _moe_nodrop(p, x):
        """Switch-MoE output for serving: top-1 routing with NO capacity
        drops — every token gets its routed expert's gated output.
        Computes all E experts and selects (E x the FFN FLOPs; at decode
        token counts that is cheap, and the result is independent of
        padding and batch composition, unlike the capacity-dropped
        training path ``parallel.expert_parallel.moe_ffn``, whose
        numbers this matches exactly whenever that path drops nothing).
        Returns the residual branch only (caller adds)."""
        d = x.shape[-1]
        lead = x.shape[:-1]
        tokens = x.reshape(-1, d)
        logits = tokens.astype(jnp.float32) @ p["router"]
        probs = jax.nn.softmax(logits, axis=-1)
        idx = jnp.argmax(probs, axis=-1)  # (S,)
        gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        h = jnp.einsum("sd,edh->esh", tokens, p["wi"].astype(x.dtype))
        h = jax.nn.gelu(h)
        out_all = jnp.einsum("esh,ehd->esd", h, p["wo"].astype(x.dtype))
        sel = out_all[idx, jnp.arange(tokens.shape[0])]  # (S, d)
        out = sel * gate[:, None].astype(x.dtype)
        return out.reshape(*lead, d)

    def _stages_decode(self, bp, caches, x, pos, t_mask):
        """One token through every (block, optional MoE) stage against
        the caches — the C=1 face of ``_stage_chunk``, run by the
        greedy/ragged scan, beam search, and the speculative draft."""
        x = x[:, None]  # (B, d) -> (B, 1, d)
        qmask = t_mask[None, :]
        new_caches = []
        for (blk, _, moe, _), (p, pm), (ck, cv) in zip(
            self._stages, bp, caches
        ):
            x, ck, cv = self._stage_chunk(
                blk, moe, p, pm, x, ck, cv, pos, qmask
            )
            new_caches.append((ck, cv))
        return x[:, 0], new_caches

    def _decode_fn(self, min_len, n_scan, steps, temp):
        """THE cached decode builder (rectangular = uniform lens). The
        prefill covers positions 0..min_len-2 — every row's prompt
        reaches at least min_len, so those are real tokens for the whole
        batch; each scanned step then advances one position for
        everyone, with the same keep-prompt / frozen masking as the
        uncached scan (rows re-embed their own prompt tokens until their
        prompt ends, then append exactly ``steps`` generated tokens)."""
        final_ln, head = self._final_ln, self._head
        seq_len = self.model.input_shape[0]

        def decode(params, state, ctx, lens, key):
            del state, key  # RNG is counter-based: (seed, emitted idx)
            bp, p_ln, p_head, embed, caches = self._decode_prologue(
                params, ctx, min_len
            )
            temps, topk, topp, seeds = self._sampling_rows(ctx.shape[0])

            def step(carry, i):
                tok, ctx, caches = carry
                pos = min_len - 1 + i
                x = embed(tok, pos)
                t_mask = jnp.arange(seq_len) <= pos
                x, new_caches = self._stages_decode(
                    bp, caches, x, pos, t_mask
                )
                x, _ = final_ln.apply(p_ln, {}, x)
                logit, _ = head.apply(p_head, {}, x)  # (B, V)
                if temp == 0.0:
                    nxt = jnp.argmax(logit, axis=-1)
                else:
                    from distkeras_tpu.serving import sampling as _sp

                    epos = jnp.maximum(pos + 1 - lens, 0)  # emitted idx
                    nxt = _sp.sample_tokens(
                        logit, temps, topk, topp, seeds, epos
                    )
                ctx, nxt = self._masked_write(ctx, lens, steps, pos, nxt)
                return (nxt, ctx, new_caches), nxt

            tok0 = ctx[:, min_len - 1]
            (_, ctx, _), _ = jax.lax.scan(
                step, (tok0, ctx, caches), jnp.arange(n_scan)
            )
            return ctx

        return jax.jit(decode)


class BeamSearchGenerator(CachedSequenceGenerator):
    """Beam-search decoding for the causal-LM family: keep the
    ``beam_width`` highest-log-probability hypotheses per prompt instead
    of one greedy path. No reference counterpart (SURVEY §5.7).

    The whole search is ONE compiled program, like the other
    generators: beams ride the batch axis of the per-block K/V caches
    ((B*W, T, H, Dh) — ``_block_decode`` is shared verbatim with cached
    greedy decode), and each scanned step expands every live beam over
    the vocabulary, takes the top ``beam_width`` of the B×(W·V) scored
    continuations, and reorders contexts/caches by parent-beam gather.
    The per-step cache gather is the classic beam cost — O(W·T·H·Dh)
    extra HBM traffic per token; serving stacks pay it for better
    sequences, which is exactly the trade this class exposes.

    ``eos_id`` finishes a hypothesis: a finished beam's only extension
    is another ``eos_id`` at zero additional log-probability, so its
    score freezes while open beams keep accumulating. Ranking during
    the search uses raw cumulative log-probability; ``length_penalty``
    (GNMT-style ``((5+L)/6)**alpha``) applies at FINAL selection only,
    favouring longer finished hypotheses at alpha > 0.

    ``beam_width=1`` is pinned equal to greedy cached decode. Scores of
    the returned sequences land in ``self.last_scores`` (raw summed
    log-prob of the winning beam, before the length penalty).
    """

    def __init__(self, model, beam_width=4, length_penalty=0.0,
                 kv_dtype=None):
        super().__init__(model, temperature=0.0, seed=0, kv_dtype=kv_dtype)
        self.beam_width = int(beam_width)
        self.length_penalty = float(length_penalty)
        self._validate_beam()
        self.last_scores = None

    def _validate_beam(self):
        """Re-checked at every generate(), like the parent's sampling
        validation: beam_width/length_penalty are mutable and key the
        compiled-fn cache, so a mutated value must hit the same
        validation the constructor applied."""
        if self.beam_width < 1:
            raise ValueError(
                f"beam_width must be >= 1; got {self.beam_width}"
            )
        vocab = self._emb.vocab_size
        if self.beam_width > vocab:
            raise ValueError(
                f"beam_width ({self.beam_width}) exceeds the vocabulary "
                f"({vocab}) — there are not that many distinct "
                "single-token continuations"
            )
        if self.length_penalty < 0:
            raise ValueError(
                f"length_penalty must be >= 0; got {self.length_penalty}"
            )

    def generate(self, prompts, steps, eos_id=None):
        """(B, P) prompts -> best-scoring continuation per row. Returns
        (B, P + steps) (or a list of eos-trimmed rows when ``eos_id`` is
        given, like the other generators). Ragged batches are not
        supported for beam search — pad/bucket upstream."""
        self._validate_beam()
        if isinstance(prompts, (list, tuple)) and len(
            {len(np.atleast_1d(p)) for p in prompts}
        ) > 1:
            raise ValueError(
                "beam search decodes rectangular batches only; pad or "
                "bucket ragged prompts upstream"
            )
        prompts, steps, seq_len = self._validate_generate_args(
            np.asarray(prompts), steps
        )
        b, p = prompts.shape
        ctx = np.zeros((b, seq_len), prompts.dtype)
        ctx[:, :p] = prompts
        eos = -1 if eos_id is None else int(eos_id)
        key = ("beam", p, steps, eos, self.beam_width, self.length_penalty)
        if key not in self._fns:
            self._fns[key] = self._beam_decode_fn(p, steps, eos)
        out, scores = self._fns[key](
            self.model.params, self.model.state, jnp.asarray(ctx)
        )
        self.last_scores = np.asarray(scores)
        out = np.asarray(out)[:, : p + steps]
        if eos_id is None:
            return out
        return [self._trim_eos(row, p, int(eos_id)) for row in out]

    def _beam_decode_fn(self, prompt_len, steps, eos):
        final_ln, head = self._final_ln, self._head
        seq_len = self.model.input_shape[0]
        W = self.beam_width
        alpha = self.length_penalty

        def decode(params, state, ctx):
            del state
            bsz = ctx.shape[0]
            bp, p_ln, p_head, embed, caches = self._decode_prologue(
                params, ctx, prompt_len
            )
            # tile beams onto the batch axis; beam 0 alone starts live
            # (cum[-inf] elsewhere), so the first expansion picks the W
            # best DISTINCT first tokens instead of W copies of one
            caches = [
                (jnp.repeat(ck, W, axis=0), jnp.repeat(cv, W, axis=0))
                for ck, cv in caches
            ]
            ctxw = jnp.repeat(ctx, W, axis=0).reshape(bsz, W, seq_len)
            cum = jnp.full((bsz, W), -jnp.inf).at[:, 0].set(0.0)
            fin = jnp.zeros((bsz, W), bool)
            glen = jnp.zeros((bsz, W), jnp.int32)
            tok = ctxw[:, :, prompt_len - 1]

            def step(carry, i):
                tok, ctxw, cum, fin, glen, caches = carry
                pos = prompt_len - 1 + i
                x = embed(tok.reshape(-1), pos)  # (B*W, d)
                t_mask = jnp.arange(seq_len) <= pos
                x, new_caches = self._stages_decode(
                    bp, caches, x, pos, t_mask
                )
                x, _ = final_ln.apply(p_ln, {}, x)
                logit, _ = head.apply(p_head, {}, x)  # (B*W, V)
                vocab = logit.shape[-1]
                logp = jax.nn.log_softmax(logit, axis=-1).reshape(
                    bsz, W, vocab
                )
                if eos >= 0:
                    # a finished beam extends only with eos, for free —
                    # its score freezes while open beams keep paying
                    only_eos = jnp.full((vocab,), -jnp.inf).at[eos].set(0.0)
                    logp = jnp.where(
                        fin[:, :, None], only_eos[None, None, :], logp
                    )
                total = (cum[:, :, None] + logp).reshape(bsz, W * vocab)
                cum, flat = jax.lax.top_k(total, W)  # (B, W) each
                parent = flat // vocab
                token = (flat % vocab).astype(tok.dtype)
                # reorder every piece of beam state by parent
                ctxw = jnp.take_along_axis(
                    ctxw, parent[:, :, None], axis=1
                )
                fin = jnp.take_along_axis(fin, parent, axis=1)
                glen = jnp.take_along_axis(glen, parent, axis=1)
                glen = glen + (~fin).astype(jnp.int32)
                if eos >= 0:
                    fin = fin | (token == eos)
                gather = (
                    jnp.arange(bsz)[:, None] * W + parent
                ).reshape(-1)  # (B*W,)
                caches = [
                    (ck[gather], cv[gather]) for ck, cv in new_caches
                ]
                ctxw = jax.lax.dynamic_update_slice_in_dim(
                    ctxw, token[:, :, None].astype(ctxw.dtype),
                    pos + 1, axis=2,
                )
                return (token, ctxw, cum, fin, glen, caches), None

            (tok, ctxw, cum, fin, glen, _), _ = jax.lax.scan(
                step, (tok, ctxw, cum, fin, glen, caches),
                jnp.arange(steps),
            )
            if alpha > 0.0:
                lp = ((5.0 + glen.astype(jnp.float32)) / 6.0) ** alpha
                final_score = cum / lp
            else:
                final_score = cum
            best = jnp.argmax(final_score, axis=1)  # (B,)
            out = jnp.take_along_axis(
                ctxw, best[:, None, None], axis=1
            )[:, 0]
            best_cum = jnp.take_along_axis(cum, best[:, None], axis=1)[:, 0]
            return out, best_cum

        return jax.jit(decode)


class SpeculativeGenerator:
    """Draft-and-verify (speculative) greedy decoding: a small DRAFT
    model proposes ``k`` tokens per round from its own KV caches, the
    TARGET model verifies all k+1 positions in ONE chunked forward, and
    the longest agreeing prefix plus the target's correction token are
    accepted. Output is EXACTLY the target's greedy decode — the draft
    only changes how many target forwards it takes to produce it. No
    reference counterpart (SURVEY §5.7).

    TPU shape: the whole decode is one compiled ``lax.while_loop`` per
    row (dynamic trip count is legal under jit; decode needs no grad),
    so acceptance-dependent progress costs zero recompiles and zero
    host round-trips. Each round is one k-step draft scan plus one
    (k+1)-token target extension — decode is memory-bound, so reading
    the target's weights once per k+1 tokens instead of once per token
    is the win; when the draft disagrees constantly the floor is one
    accepted token per round (plain decode plus draft overhead).

    Rows decode sequentially through one compiled program (per-row
    positions diverge with acceptance; batching them needs per-row
    masks/scatters — a future lift). ``last_rounds`` records verify
    rounds per row; steps/rounds is the measured mean acceptance.

    Numerics: "exactly the target's greedy decode" is exact up to FP
    associativity — the verify chunk contracts its attention einsums in
    a different order than the per-token cached path, a ~1e-6
    difference that could flip argmax only on near-ties (never observed
    on the pinned seeds; trained models have margins). The tests pin
    exact equality on random AND trained models, and the self-draft
    acceptance ceiling exactly.
    """

    def __init__(self, target, draft, k=4, kv_dtype=None):
        self._t = CachedSequenceGenerator(target, kv_dtype=kv_dtype)
        self._d = CachedSequenceGenerator(draft, kv_dtype=kv_dtype)
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"k must be >= 1; got {k}")
        if self._t._emb.vocab_size != self._d._emb.vocab_size:
            raise ValueError(
                "target and draft must share a vocabulary; got "
                f"{self._t._emb.vocab_size} vs {self._d._emb.vocab_size}"
            )
        if target.input_shape[0] != draft.input_shape[0]:
            raise ValueError(
                "target and draft must be built to the same sequence "
                f"length; got {target.input_shape[0]} vs "
                f"{draft.input_shape[0]}"
            )
        self.target, self.draft = target, draft
        self._fns = {}
        self.last_rounds = None

    def generate(self, prompts, steps, eos_id=None):
        """(B, P) prompts -> the TARGET's greedy continuation, decoded
        speculatively. Same return conventions as the other generators
        ((B, P+steps) array; list of trimmed rows with ``eos_id``)."""
        self.k = int(self.k)
        if self.k < 1:  # re-validated: k is mutable and keys the cache
            raise ValueError(f"k must be >= 1; got {self.k}")
        prompts, steps, seq_len = self._t._validate_generate_args(
            np.asarray(prompts), steps
        )
        b, p = prompts.shape
        key = (p, steps, self.k)
        if key not in self._fns:
            self._fns[key] = self._spec_decode_fn(p, steps)
        outs, rounds = [], []
        for row in prompts:
            ctx = np.zeros((1, seq_len), prompts.dtype)
            ctx[0, :p] = row
            out, n_rounds = self._fns[key](
                self.target.params, self.draft.params, jnp.asarray(ctx)
            )
            outs.append(np.asarray(out)[0, : p + steps])
            rounds.append(int(n_rounds))
        self.last_rounds = np.asarray(rounds)
        out = np.stack(outs)
        if eos_id is None:
            return out
        return [
            SequenceGenerator._trim_eos(r, p, int(eos_id)) for r in out
        ]

    def _extend(self, gen, bp, caches, x, pos, t_pad):
        """Run a (1, C, d) token chunk at positions pos..pos+C-1 through
        ``gen``'s stages against full-length caches: the verify side of
        a round — the same ``_stage_chunk`` body as every other decode
        path, at C=k+1 with chunk-causal masking."""
        c = x.shape[1]
        qmask = (
            jnp.arange(t_pad)[None, :] <= (pos + jnp.arange(c))[:, None]
        )
        new_caches = []
        for (blk, _, moe, _), (p, pm), (ck, cv) in zip(
            gen._stages, bp, caches
        ):
            x, ck, cv = gen._stage_chunk(
                blk, moe, p, pm, x, ck, cv, pos, qmask
            )
            new_caches.append((ck, cv))
        return x, new_caches

    def _spec_decode_fn(self, prompt_len, steps):
        k = self.k
        seq_len = self.target.input_shape[0]
        # draft chunks and verify writes run up to k positions past the
        # last kept token; pad the working buffers so overrun K/V lands
        # in masked scratch instead of clamping onto real positions
        t_pad = seq_len + k + 1
        tgen, dgen = self._t, self._d

        def decode(t_params, d_params, ctx):
            ctx = jnp.concatenate(
                [ctx, jnp.zeros((1, t_pad - seq_len), ctx.dtype)], axis=1
            )
            t_bp, t_ln, t_head, t_embed, t_caches = tgen._decode_prologue(
                t_params, ctx, prompt_len, cache_len=t_pad
            )
            d_bp, d_ln, d_head, d_embed, d_caches = dgen._decode_prologue(
                d_params, ctx, prompt_len, cache_len=t_pad
            )
            t_mask_grid = jnp.arange(t_pad)

            def draft_chunk(ctx, d_caches, pos):
                """k greedy draft tokens from ctx[pos]; returns (toks
                (k,), caches). The scan runs k+1 steps, discarding the
                last proposal: step j writes the draft's K/V at position
                pos+j, and after a FULLY accepted round the next round
                starts at pos+k+1 — without the extra step, position
                pos+k would stay a zero cache row the next draft chunk
                silently attends over, poisoning every post-full-accept
                proposal (found as a guaranteed rejection after each
                full accept: self-draft measured 5-6 rounds for the
                3-round ceiling)."""

                def step(carry, j):
                    tok, caches = carry
                    x = d_embed(tok, pos + j)
                    t_mask = t_mask_grid <= pos + j
                    x, caches = dgen._stages_decode(
                        d_bp, caches, x, pos + j, t_mask
                    )
                    x, _ = dgen._final_ln.apply(d_ln, {}, x)
                    logit, _ = dgen._head.apply(d_head, {}, x)
                    nxt = jnp.argmax(logit, axis=-1).astype(tok.dtype)
                    return (nxt, caches), nxt[0]

                tok0 = jax.lax.dynamic_index_in_dim(
                    ctx, pos, axis=1, keepdims=False
                )  # (1,)
                (_, caches), toks = jax.lax.scan(
                    step, (tok0, d_caches), jnp.arange(k + 1)
                )
                return toks[:k], caches

            def body(state):
                ctx, t_caches, d_caches, pos, n_gen, rounds = state
                d_toks, d_caches = draft_chunk(ctx, d_caches, pos)
                # target verifies positions pos..pos+k in one chunk
                tok0 = jax.lax.dynamic_index_in_dim(
                    ctx, pos, axis=1, keepdims=False
                )
                chunk = jnp.concatenate([tok0, d_toks])  # (k+1,)
                x = jax.vmap(t_embed, in_axes=(0, 0))(
                    chunk, pos + jnp.arange(k + 1)
                )[None]  # (1, k+1, d)
                x, t_caches = self._extend(
                    tgen, t_bp, t_caches, x, pos, t_pad
                )
                x, _ = tgen._final_ln.apply(t_ln, {}, x)
                logit, _ = tgen._head.apply(t_head, {}, x)  # (1, k+1, V)
                t_arg = jnp.argmax(logit[0], axis=-1).astype(ctx.dtype)
                # accept the agreeing prefix + the target's correction
                agree = d_toks == t_arg[:k]
                n_acc = jnp.argmin(
                    jnp.concatenate([agree, jnp.array([False])])
                )  # first disagreement, k if all agree
                n_new = jnp.minimum(n_acc + 1, steps - n_gen)
                # masked segment write at pos+1 (beyond-budget positions
                # keep their existing — zero-pad — values)
                cur = jax.lax.dynamic_slice(
                    ctx, (0, pos + 1), (1, k + 1)
                )[0]
                seg = jnp.where(jnp.arange(k + 1) < n_new, t_arg, cur)
                ctx = jax.lax.dynamic_update_slice(
                    ctx, seg[None], (0, pos + 1)
                )
                return (
                    ctx, t_caches, d_caches, pos + n_new, n_gen + n_new,
                    rounds + 1,
                )

            def cond(state):
                return state[4] < steps

            state = (
                ctx, t_caches, d_caches,
                jnp.int32(prompt_len - 1), jnp.int32(0), jnp.int32(0),
            )
            ctx, _, _, _, _, rounds = jax.lax.while_loop(cond, body, state)
            return ctx[:, :seq_len], rounds

        return jax.jit(decode)
