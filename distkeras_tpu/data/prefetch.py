"""Background prefetch — overlap host input work with device compute.

The reference hid input latency behind Spark's executor iterators; the
round-1 rebuild's hot loops instead stacked + ``device_put`` the NEXT
window on the critical path after blocking on the previous one (VERDICT r1
weak #5 — invisible on CPU tests, real throughput lost on TPU).

:class:`Prefetcher` is the fix: a bounded one-thread pipeline that pulls
items from a source iterator and maps a ``prepare`` function (typically
stack-the-window + ``device_put``) up to ``depth`` items ahead of the
consumer. While the chip runs window N, the host thread is already staging
window N+1's buffers — double buffering, since jax dispatch is async and
``device_put`` from a second thread overlaps compute.

Order is preserved exactly (single worker thread + FIFO queue), so
trainers keep their bit-identical trajectories with prefetch on or off.
Exceptions in the source/prepare re-raise at the consumption point, and
``close()`` (also called by ``__exit__`` and generator teardown) stops the
thread without draining.
"""

from __future__ import annotations

import queue
import threading

_DONE = object()


class Prefetcher:
    """Iterate ``prepare(item) for item in source`` with a ``depth``-deep
    background pipeline. ``depth=0`` degrades to synchronous mapping."""

    def __init__(self, source, prepare=None, depth: int = 2):
        self._prepare = prepare if prepare is not None else (lambda x: x)
        self._depth = int(depth)
        if self._depth <= 0:
            self._iter = iter(source)
            self._queue = None
            self._thread = None
            return
        self._iter = None
        self._terminal = None  # StopIteration or the propagated exception
        self._queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._fill, args=(iter(source),), daemon=True
        )
        self._thread.start()

    def _fill(self, source):
        try:
            for item in source:
                if self._stop.is_set():
                    return
                out = self._prepare(item)
                while not self._stop.is_set():
                    try:
                        self._queue.put(out, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
            self._put_forever(_DONE)
        except BaseException as exc:  # noqa: BLE001 — re-raised at consumer
            self._put_forever(exc)

    def _put_forever(self, item):
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._queue is None:  # synchronous fallback
            return self._prepare(next(self._iter))
        if self._terminal is not None:
            # the worker puts its sentinel exactly once and exits; without
            # this latch a second next() after exhaustion/error would block
            # on an empty queue forever
            raise self._terminal
        item = self._queue.get()
        if item is _DONE:
            self._terminal = StopIteration()
            raise self._terminal
        if isinstance(item, BaseException):
            self._terminal = item
            raise item
        return item

    def close(self):
        if self._thread is not None:
            self._stop.set()
            # unblock a put-blocked worker
            try:
                self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
