"""File-sharded streaming Dataset — the beyond-RAM input pipeline.

The reference's data plane is Spark: DataFrames partitioned across executor
JVMs meant a dataset never had to fit on one host (reference:
distkeras/trainers.py -> DistributedTrainer.train repartitions the frame;
workers iterate partition rows). The round-1 rebuild's ``Dataset`` is fully
in-memory, which caps it at host RAM (VERDICT r1 missing #3 — BASELINE
config 5's ImageNet-scale shape was unfeedable). ``StreamingDataset`` is the
TPU-native replacement for Spark's storage tier:

- data lives in numbered ``.npz`` shards on disk (one zip of named column
  arrays each, written by :func:`write_shards`, plus a ``shards.json``
  sidecar with per-shard row counts so opening a dataset reads zero rows);
- iteration loads ONE shard at a time, so peak host memory is one shard
  regardless of dataset size;
- ``batches()`` carries remainder rows across shard boundaries — batch
  shapes stay static (an XLA requirement) and rows are never dropped at
  shard seams, only the final global remainder;
- ``shuffle(seed)`` permutes shard order and rows within each shard
  deterministically (the standard out-of-core approximation of a global
  shuffle — exact global shuffles would need all rows resident);
- ``partition(n)`` deals whole shards round-robin to workers — the
  ``repartition(num_workers)`` analog at shard granularity;
- ``map(fn)`` applies a per-chunk transform (e.g. the preprocessing
  transformers) lazily as each shard is loaded.

Trainers accept a StreamingDataset anywhere they accept a Dataset — the
contract is ``__len__`` / ``columns`` / ``shuffle`` / ``partition`` /
``batches``.
"""

from __future__ import annotations

import json
import os
import zipfile

import numpy as np

_META = "shards.json"


class ShardWriter:
    """Incremental shard writer: ``add(columns_dict)`` appends one shard
    file; ``close()`` publishes the ``shards.json`` sidecar. Lets a
    generator larger than RAM be sharded chunk by chunk into ONE directory
    that :func:`open_shards` round-trips."""

    def __init__(self, out_dir: str):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self._paths = []
        self._rows = []
        self._columns = None

    def add(self, columns: dict) -> str:
        cols = {k: np.asarray(v) for k, v in columns.items()}
        names = sorted(cols)
        if self._columns is None:
            self._columns = names
        elif names != self._columns:
            raise ValueError(
                f"shard columns {names} != first shard's {self._columns}"
            )
        lens = {k: len(v) for k, v in cols.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"column length mismatch within shard: {lens}")
        path = os.path.join(self.out_dir, f"shard_{len(self._paths):05d}.npz")
        np.savez(path, **cols)
        self._paths.append(path)
        self._rows.append(len(next(iter(cols.values()))))
        return path

    def close(self) -> list:
        if not self._paths:
            raise ValueError("no shards written")
        with open(os.path.join(self.out_dir, _META), "w") as f:
            json.dump(
                {
                    "shards": [os.path.basename(p) for p in self._paths],
                    "rows": self._rows,
                    "columns": self._columns,
                },
                f,
            )
        return list(self._paths)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        return False


def write_shards(dataset, out_dir: str, rows_per_shard: int) -> list:
    """Split ``dataset`` (Dataset or dict of column arrays) into ``.npz``
    shards under ``out_dir``; returns the shard paths. Also writes the
    ``shards.json`` sidecar (row counts + columns) so reopening is O(1)."""
    cols = (
        {k: np.asarray(dataset[k]) for k in dataset.columns}
        if hasattr(dataset, "columns")
        else {k: np.asarray(v) for k, v in dataset.items()}
    )
    n = len(next(iter(cols.values())))
    rows_per_shard = int(rows_per_shard)
    if rows_per_shard <= 0:
        raise ValueError("rows_per_shard must be positive")
    with ShardWriter(out_dir) as writer:
        for start in range(0, n, rows_per_shard):
            stop = min(start + rows_per_shard, n)
            writer.add({k: v[start:stop] for k, v in cols.items()})
    return writer._paths


def _peek_npz_rows(path: str) -> int:
    """Leading-axis length of the arrays in an ``.npz`` without reading any
    array data: parse the first member's npy header through the zip."""
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        if not names:
            raise ValueError(f"empty npz shard {path!r}")
        with z.open(names[0]) as f:
            version = np.lib.format.read_magic(f)
            if version >= (2, 0):
                shape, _, _ = np.lib.format.read_array_header_2_0(f)
            else:
                shape, _, _ = np.lib.format.read_array_header_1_0(f)
    return shape[0] if shape else 0


def _peek_npz_columns(path: str) -> list:
    """Column names of an ``.npz`` shard from the zip directory alone."""
    with zipfile.ZipFile(path) as z:
        return sorted(
            name[: -len(".npy")] for name in z.namelist() if name.endswith(".npy")
        )


def open_shards(directory: str) -> "StreamingDataset":
    """Open a shard directory written by :func:`write_shards` (or any
    directory of homogeneous ``.npz`` files)."""
    meta_path = os.path.join(directory, _META)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        paths = [os.path.join(directory, name) for name in meta["shards"]]
        return StreamingDataset(
            paths, rows=meta["rows"], columns=meta.get("columns")
        )
    paths = sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if name.endswith(".npz")
    )
    if not paths:
        raise FileNotFoundError(f"no .npz shards in {directory!r}")
    return StreamingDataset(paths)


class StreamingDataset:
    """Dataset streamed shard-by-shard from ``.npz`` files (see module doc)."""

    def __init__(self, shard_paths, rows=None, transforms=(), seed=None, columns=None):
        self._paths = list(shard_paths)
        if not self._paths:
            raise ValueError("StreamingDataset needs at least one shard")
        self._rows = (
            [int(r) for r in rows]
            if rows is not None
            else [_peek_npz_rows(p) for p in self._paths]
        )
        if len(self._rows) != len(self._paths):
            raise ValueError("rows metadata does not match shard count")
        self._transforms = tuple(transforms)
        self._seed = seed  # None = no shuffle; int = shard+row permutation
        # known only without transforms (a map() can rename columns)
        self._columns = list(columns) if columns and not transforms else None

    # -- Dataset contract ----------------------------------------------------

    def __len__(self):
        return sum(self._rows)

    @property
    def columns(self):
        if self._columns is None:
            # no transforms: names come from the zip directory, no data read;
            # with transforms the first chunk must actually run through them
            self._columns = (
                _peek_npz_columns(self._paths[0])
                if not self._transforms
                else sorted(self._load_chunk(0).keys())
            )
        return list(self._columns)

    def shuffle(self, seed) -> "StreamingDataset":
        """Deterministic out-of-core shuffle: permute shard order and the
        rows within each shard (chunk-local; see module doc)."""
        return StreamingDataset(
            self._paths,
            self._rows,
            self._transforms,
            seed=int(seed),
            columns=self._columns,
        )

    def partition(self, num_workers: int):
        """Deal whole shards round-robin; every worker streams its own
        subset of files (the repartition analog). Requires at least one
        shard per worker."""
        num_workers = int(num_workers)
        if num_workers > len(self._paths):
            raise ValueError(
                f"{num_workers} workers need >= {num_workers} shards, "
                f"have {len(self._paths)} — re-shard with smaller "
                "rows_per_shard"
            )
        parts = []
        for w in range(num_workers):
            idx = list(range(w, len(self._paths), num_workers))
            parts.append(
                StreamingDataset(
                    [self._paths[i] for i in idx],
                    [self._rows[i] for i in idx],
                    self._transforms,
                    self._seed,
                    columns=self._columns,
                )
            )
        return parts

    def map(self, fn) -> "StreamingDataset":
        """Lazy per-chunk transform: ``fn(dict of arrays) -> dict`` runs as
        each shard is loaded (how preprocessing composes with streaming)."""
        return StreamingDataset(
            self._paths, self._rows, (*self._transforms, fn), self._seed
        )

    def batches(self, batch_size: int, columns=None, drop_remainder=True):
        """Yield static-shape minibatches, carrying remainders across shard
        seams; only the final global remainder is dropped."""
        batch_size = int(batch_size)
        order = list(range(len(self._paths)))
        rng = (
            np.random.default_rng(self._seed) if self._seed is not None else None
        )
        if rng is not None:
            order = list(rng.permutation(len(self._paths)))
        carry = None
        for shard_i in order:
            chunk = self._load_chunk(shard_i)
            if rng is not None:
                perm = rng.permutation(len(next(iter(chunk.values()))))
                chunk = {k: v[perm] for k, v in chunk.items()}
            cols = columns or sorted(chunk)
            chunk = {k: chunk[k] for k in cols}
            if carry is not None:
                chunk = {
                    k: np.concatenate([carry[k], chunk[k]]) for k in cols
                }
            n = len(next(iter(chunk.values())))
            stop = (n // batch_size) * batch_size
            for i in range(0, stop, batch_size):
                yield {k: v[i : i + batch_size] for k, v in chunk.items()}
            carry = (
                {k: v[stop:] for k, v in chunk.items()} if stop < n else None
            )
        if carry is not None and not drop_remainder:
            yield carry

    def num_batches(self, batch_size: int, drop_remainder=True) -> int:
        n = len(self)
        return n // batch_size if drop_remainder else -(-n // batch_size)

    # -- internals -----------------------------------------------------------

    def _load_chunk(self, shard_i: int) -> dict:
        with np.load(self._paths[shard_i], allow_pickle=False) as z:
            chunk = {k: z[k] for k in z.files}
        for fn in self._transforms:
            chunk = fn(chunk)
        return chunk

    def __repr__(self):
        return (
            f"StreamingDataset(shards={len(self._paths)}, rows={len(self)}, "
            f"seed={self._seed})"
        )
