"""Dataset loaders.

Replaces the examples' ``spark.read.csv`` plumbing (reference:
examples/mnist.py loads MNIST CSV into a DataFrame). Two tiers:

- ``load_csv`` — real data from disk in the same CSV layout the reference
  examples consume (label column + flat pixel/feature columns).
- ``synthetic_*`` — deterministic, *learnable* generated stand-ins (class
  prototypes + noise) for the sandbox, where no dataset downloads exist.
  They drive the convergence/integration tests and the benchmark harness.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def load_csv(path, label_col="label", dtype=np.float32,
             label_dtype=np.int64) -> Dataset:
    """CSV with a header row -> Dataset with 'features' + 'label' columns.

    The numeric body parses through the native C++ reader
    (distkeras_tpu/native/dkt_data.cpp via data/native.py) when available;
    a pure-Python csv loop is the fallback (DKT_NO_NATIVE=1 forces it).
    ``label_dtype`` defaults to int64 (classification ids); regression
    CSVs pass a float dtype to keep continuous targets.
    """
    from distkeras_tpu.data import native

    with open(path, newline="") as f:
        header = next(csv.reader(f))
    # the native parser is float32; wider dtypes must keep full precision,
    # so they always take the Python path
    if np.dtype(dtype).itemsize <= 4 and native.available():
        rows, had_header = native.read_csv(path)
        if not had_header:
            rows = rows[1:]  # contract: first line is always the header
        rows = rows.astype(dtype, copy=False)
    else:
        with open(path, newline="") as f:
            reader = csv.reader(f)
            next(reader)  # header
            rows = np.asarray([[float(v) for v in row] for row in reader], dtype)
    if label_col in header:
        li = header.index(label_col)
        label = rows[:, li].astype(label_dtype)
        feats = np.delete(rows, li, axis=1)
    else:
        label = rows[:, 0].astype(label_dtype)
        feats = rows[:, 1:]
    return Dataset({"features": feats.astype(dtype), "label": label})


def _apply_label_noise(labels, num_classes, frac, rng):
    """Resample ``frac`` of the labels uniformly over all classes. This
    plants a DETERMINISTIC Bayes ceiling: no classifier can score above
    ~(1 - frac) + frac/C held-out, so accuracy cannot saturate at 1.0000
    and the epochs-to-target axis stays discriminating (VERDICT r3 weak
    #6: the noise-free prototypes were too easy — every optimizer ended
    at 1.0 and the matrix measured nothing)."""
    if frac <= 0.0:
        return labels
    flip = rng.random(labels.shape) < frac
    return np.where(flip, rng.integers(0, num_classes, labels.shape), labels)


def _prototype_classification(
    n, num_classes, feature_shape, noise, seed, flatten=False,
    protos_per_class=1, label_noise=0.0,
):
    """Per-class random prototypes + gaussian noise: separable but nontrivial.

    ``protos_per_class`` > 1 makes each class a MIXTURE of prototypes
    (nonlinear decision boundary — slower to learn, so optimizers
    separate); ``label_noise`` resamples that fraction of labels for a
    hard accuracy ceiling < 1 (see ``_apply_label_noise``)."""
    rng = np.random.default_rng(seed)
    dim = int(np.prod(feature_shape))
    protos = rng.normal(
        0.0, 1.0, (num_classes, protos_per_class, dim)
    ).astype(np.float32)
    labels = rng.integers(0, num_classes, n)
    # the comp draw happens ONLY for real mixtures: default args must
    # reproduce the exact r2/r3-calibrated RNG stream (tests pin it)
    if protos_per_class > 1:
        comp = rng.integers(0, protos_per_class, n)
    else:
        comp = np.zeros(n, np.int64)
    x = protos[labels, comp] + rng.normal(0.0, noise, (n, dim)).astype(np.float32)
    labels = _apply_label_noise(labels, num_classes, label_noise, rng)
    # squash into [0, 255] so the MinMax(0..255) pipeline stays meaningful
    x = (255.0 / (1.0 + np.exp(-x))).astype(np.float32)
    if not flatten:
        x = x.reshape(n, *feature_shape)
    return Dataset({"features": x, "label": labels.astype(np.int64)})


def synthetic_mnist(n=8192, noise=1.0, seed=0, flat=True,
                    protos_per_class=1, label_noise=0.0,
                    spatial=False) -> Dataset:
    """MNIST-shaped: features (784,) in [0,255], labels 0..9.

    ``spatial=True`` draws class evidence as low-spatial-frequency
    patterns (`_spatial_prototype_classification`) instead of iid pixels
    — the structure real MNIST digits actually have, and the statistics
    conv stacks exploit (iid prototypes are adversarial to weight
    sharing: r4 calibration saw the CNN sit at chance for 6 epochs on
    the iid mixture task while the spatial CIFAR config learned
    healthily). The benchmark matrix's CNN config uses this."""
    if spatial:
        ds = _spatial_prototype_classification(
            n, 10, (28, 28, 1), noise, seed,
            protos_per_class=protos_per_class, label_noise=label_noise,
        )
        if flat:
            ds = ds.with_column(
                "features", ds["features"].reshape(n, 784)
            )
        return ds
    return _prototype_classification(
        n, 10, (28, 28, 1), noise, seed, flatten=flat,
        protos_per_class=protos_per_class, label_noise=label_noise,
    )


def synthetic_higgs(n=8192, num_features=30, noise=1.5, seed=1) -> Dataset:
    """ATLAS-Higgs-shaped binary tabular task with ~30 physics features."""
    rng = np.random.default_rng(seed)
    w = rng.normal(0.0, 1.0, (num_features,)).astype(np.float32)
    x = rng.normal(0.0, 1.0, (n, num_features)).astype(np.float32)
    logits = x @ w + 0.5 * (x[:, 0] * x[:, 1]) + noise * rng.normal(0.0, 1.0, n)
    label = (logits > 0).astype(np.int64)
    return Dataset({"features": x, "label": label})


def _coarse_grid(h, w, coarse):
    """Largest pattern-grid size <= ``coarse`` dividing both h and w (>=1),
    so any image size upsamples cleanly."""
    g = coarse
    while g > 1 and (h % g or w % g):
        g -= 1
    return g


def _spatial_prototype_classification(
    n, num_classes, feature_shape, noise, seed, coarse=4, proto_seed=None,
    protos_per_class=1, label_noise=0.0,
):
    """Image-shaped prototype task with SPATIAL structure: each class is a
    random ``coarse x coarse`` pattern upsampled to the full resolution, so
    class evidence lives in low spatial frequencies — the statistics conv
    + pooling stacks are built to exploit. (The iid-pixel prototypes of
    `_prototype_classification` are adversarial to conv weight sharing: an
    MLP aces them while a VGG/ResNet sits at chance for epochs — r2
    calibration.) Separable but noisy, like its flat counterpart.

    ``proto_seed``: seed of the label->pattern mapping, defaulting to
    ``seed``. Callers generating one logical dataset in several chunks
    (shard writers, separate train/eval draws) MUST pin proto_seed across
    chunks while varying ``seed`` — otherwise every chunk defines class k
    as a different pattern and the combined task is unlearnable."""
    proto_rng = np.random.default_rng(seed if proto_seed is None else proto_seed)
    rng = np.random.default_rng(seed)
    h, w, c = feature_shape
    g = _coarse_grid(h, w, coarse)
    protos = proto_rng.normal(
        0.0, 1.0, (num_classes, protos_per_class, g, g, c)
    ).astype(np.float32)
    protos = np.repeat(np.repeat(protos, h // g, axis=2), w // g, axis=3)
    labels = rng.integers(0, num_classes, n)
    # comp draw only for real mixtures (default RNG stream is pinned by
    # r2/r3-calibrated tests — see the flat generator)
    if protos_per_class > 1:
        comp = rng.integers(0, protos_per_class, n)
    else:
        comp = np.zeros(n, np.int64)
    x = protos[labels, comp] + rng.normal(
        0.0, noise, (n, h, w, c)
    ).astype(np.float32)
    labels = _apply_label_noise(labels, num_classes, label_noise, rng)
    x = (255.0 / (1.0 + np.exp(-x))).astype(np.float32)
    return Dataset({"features": x, "label": labels.astype(np.int64)})


def synthetic_cifar10(n=4096, noise=1.0, seed=2, proto_seed=None,
                      protos_per_class=1, label_noise=0.0) -> Dataset:
    """CIFAR-shaped: features (32, 32, 3) in [0,255], labels 0..9.
    Class signal is low-spatial-frequency (see
    `_spatial_prototype_classification`; pin ``proto_seed`` when drawing
    one logical dataset with several seeds)."""
    return _spatial_prototype_classification(
        n, 10, (32, 32, 3), noise, seed, proto_seed=proto_seed,
        protos_per_class=protos_per_class, label_noise=label_noise,
    )


def synthetic_imagenet(
    n=512, num_classes=1000, size=64, noise=0.5, seed=3, proto_seed=None,
    protos_per_class=1, label_noise=0.0,
) -> Dataset:
    """ImageNet-shaped smoke data (reduced spatial size by default).
    Class signal is low-spatial-frequency (see
    `_spatial_prototype_classification`; pin ``proto_seed`` when drawing
    one logical dataset with several seeds)."""
    return _spatial_prototype_classification(
        n, num_classes, (size, size, 3), noise, seed, proto_seed=proto_seed,
        protos_per_class=protos_per_class, label_noise=label_noise,
    )


def synthetic_sequences(
    n=4096, seq_len=64, vocab=32, num_classes=2, markers=None, seed=0
) -> Dataset:
    """Token-sequence classification: random background tokens with the
    class's marker token planted at random positions. Learnable by any
    attention/embedding model; drives the transformer family tests."""
    rng = np.random.default_rng(seed)
    markers = markers if markers is not None else max(2, seq_len // 8)
    if vocab <= num_classes + 1:
        # markers occupy tokens 1..C and background tokens draw from
        # [C+1, vocab) — vocab == C+1 leaves that range empty
        raise ValueError(
            "vocab must exceed num_classes + 1 (markers are 1..C, background "
            "tokens need a non-empty [C+1, vocab) range)"
        )
    x = rng.integers(num_classes + 1, vocab, (n, seq_len))
    labels = rng.integers(0, num_classes, n)
    pos = rng.random((n, seq_len)).argsort(axis=1)[:, :markers]
    x[np.arange(n)[:, None], pos] = (labels + 1)[:, None]
    return Dataset({"features": x.astype(np.int32), "label": labels.astype(np.int64)})


def digits(path=None, flat=True) -> Dataset:
    """REAL handwritten-digit data, shipped in-repo: 1,797 8x8 grayscale
    images (10 classes, 43 writers — the UCI optical-recognition test set,
    via scikit-learn) stored as ``digits.csv`` next to this module and
    parsed through the SAME ``load_csv`` + native-C++ ingestion path the
    reference's MNIST CSV examples used (reference: examples/mnist.py
    loads MNIST CSV). This breaks the synthetic-data circularity (VERDICT
    r2 missing #1): accuracy numbers on this set are measured against
    real-world data the builder did not design. Pixel values are 0..16;
    ``flat=False`` reshapes to (8, 8, 1) image layout."""
    path = path or os.path.join(os.path.dirname(__file__), "digits.csv")
    ds = load_csv(path)
    if not flat:
        x = ds["features"].reshape(len(ds), 8, 8, 1)
        ds = ds.with_column("features", x)
    return ds


def breast_cancer(path=None) -> Dataset:
    """REAL binary tabular data, shipped in-repo: the 569-row Wisconsin
    diagnostic breast-cancer set (30 real-valued features, 2 classes, via
    scikit-learn) stored as ``breast_cancer.csv`` next to this module and
    parsed through the same ``load_csv`` + native-C++ ingestion path as
    ``digits()``. The real tabular counterpart of the ATLAS-Higgs CSV the
    reference's workflow notebook trained on (reference:
    examples/workflow.ipynb loads a 30-feature physics CSV): same feature
    count, same binary target, and — like ``digits`` — accuracy measured
    against data the builder did not design (VERDICT r3 missing #1).
    Features are raw (wildly different scales); pair with
    ``StandardScaleTransformer``."""
    path = path or os.path.join(os.path.dirname(__file__), "breast_cancer.csv")
    return load_csv(path)


def diabetes(path=None) -> Dataset:
    """REAL regression data, shipped in-repo: the 442-row sklearn
    diabetes set (10 standardized clinical features, continuous disease-
    progression target 25..346) as ``diabetes.csv``, parsed through the
    same ``load_csv`` path as ``digits``/``breast_cancer``. The
    regression face of the reference's arbitrary-Keras-model support
    (reference: distkeras/trainers.py trains whatever model/loss the
    user compiled — including regressors); pairs with ``loss="mse"``,
    ``zoo.tabular_regressor`` and ``RSquaredEvaluator``. The target
    comes back as a (n, 1) float32 column so it broadcasts correctly
    against the regressor's (B, 1) predictions."""
    path = path or os.path.join(os.path.dirname(__file__), "diabetes.csv")
    ds = load_csv(path, label_dtype=np.float32)
    return ds.with_column("label", ds["label"].reshape(-1, 1))


def mnist(path=None, n=8192, seed=0, flat=True) -> Dataset:
    """Real MNIST CSV if available (path or $DISTKERAS_MNIST_CSV), else synthetic."""
    path = path or os.environ.get("DISTKERAS_MNIST_CSV")
    if path and os.path.exists(path):
        ds = load_csv(path)
        if not flat:
            x = ds["features"].reshape(len(ds), 28, 28, 1)
            ds = ds.with_column("features", x)
        return ds
    return synthetic_mnist(n=n, seed=seed, flat=flat)


def text_corpus(path=None, seq_len=128, stride=None, vocab_size=256) -> Dataset:
    """Byte-level LM windows from a REAL text file — the causal-LM
    family's data path. No reference counterpart (no sequence workloads
    upstream, SURVEY §5.7).

    The file's bytes become tokens 0..255 (``vocab_size`` must be >= 256
    and matches ``zoo.transformer_lm(vocab_size=...)``); overlapping
    windows of ``seq_len`` bytes (default stride seq_len // 2) form the
    rows, with ``label`` == ``features`` (the next-token loss shifts
    targets internally). Defaults to the repository's own LICENSE text —
    real prose shipped in-repo, in the same spirit as ``digits()``.
    """
    if path is None:
        path = default_corpus_path()
    if vocab_size < 256:
        raise ValueError(f"byte-level corpus needs vocab_size >= 256; "
                         f"got {vocab_size}")
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    if len(data) < seq_len + 1:
        raise ValueError(
            f"corpus {path!r} has {len(data)} bytes < seq_len+1 ({seq_len + 1})"
        )
    stride = stride if stride is not None else max(1, seq_len // 2)
    if stride < 1:
        raise ValueError(f"stride must be >= 1; got {stride}")
    x = np.lib.stride_tricks.sliding_window_view(data, seq_len)[::stride]
    x = np.ascontiguousarray(x).astype(np.int32)
    return Dataset({"features": x, "label": x})


def default_corpus_path() -> str:
    """The real-text default for ``text_corpus``: the packaged GPL text
    (``data/corpus.txt``, a copy of the repository LICENSE declared in
    package-data like ``digits.csv``), so the no-path default works from
    an installed wheel, not just a source checkout."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "corpus.txt")
