"""Host-side data pipeline: columnar Dataset, transformers, loaders.

Replaces the reference's Spark layer (L0/L5): ``Dataset`` stands in for the
Spark DataFrame, the transformers mirror distkeras/transformers.py, and the
loaders replace ``spark.read`` + examples' CSV plumbing. Batches are built on
host as numpy and shipped to devices by the trainers (the trainers own
device placement/sharding).
"""

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import (
    Transformer,
    MinMaxTransformer,
    OneHotTransformer,
    DenseTransformer,
    ReshapeTransformer,
    LabelIndexTransformer,
)
from distkeras_tpu.data import loaders
