"""ctypes bridge to the native (C++) data-path library.

Builds ``distkeras_tpu/native/dkt_data.cpp`` into a shared library on first
use (g++ -O3 -shared -fPIC, compiled to a temp file and published
atomically with os.replace, rebuilt when the source is newer) and exposes
it with a pure-Python fallback contract: callers check ``available()`` and
fall back when the toolchain is missing or ``DKT_NO_NATIVE=1``; calling an
entry point while unavailable raises a clean RuntimeError.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "dkt_data.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "_dkt_data.so")

_lock = threading.Lock()
_lib = None
_build_failed = False


def _disabled() -> bool:
    return os.environ.get("DKT_NO_NATIVE", "") == "1"


def _build() -> bool:
    # compile to a private temp file, publish with an atomic rename:
    # a concurrent process can never dlopen a half-written .so. Any
    # filesystem/toolchain failure (read-only package dir, missing g++)
    # degrades to the Python fallback instead of raising.
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(_SO))
        os.close(fd)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError) as e:
        logger.warning("native data library build failed (%s); using Python", e)
        if tmp is not None and os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def _load():
    global _lib, _build_failed
    if _disabled():
        return None
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            stale = not os.path.exists(_SO) or os.path.getmtime(
                _SO
            ) < os.path.getmtime(_SRC)
        except OSError:  # e.g. source missing from a stripped install
            stale = not os.path.exists(_SO)
        if stale:
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            logger.warning("native data library load failed (%s)", e)
            _build_failed = True
            return None
        lib.dkt_csv_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.dkt_csv_dims.restype = ctypes.c_int
        lib.dkt_csv_load.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.dkt_csv_load.restype = ctypes.c_int
        lib.dkt_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.dkt_free.restype = None
        lib.dkt_gather_rows_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.dkt_gather_rows_f32.restype = None
        _lib = lib
        return _lib


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native data library unavailable (no C++ toolchain, build "
            "failure, or DKT_NO_NATIVE=1) — use the Python fallback path"
        )
    return lib


def available() -> bool:
    """True when the native library is usable (built or buildable)."""
    return _load() is not None


def csv_dims(path: str):
    """(rows, cols, has_header) for a numeric CSV."""
    lib = _require()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    header = ctypes.c_int()
    rc = lib.dkt_csv_dims(
        path.encode(), ctypes.byref(rows), ctypes.byref(cols),
        ctypes.byref(header),
    )
    if rc != 0:
        raise OSError(f"native csv_dims failed for {path!r}")
    return rows.value, cols.value, bool(header.value)


def read_csv(path: str) -> tuple[np.ndarray, bool]:
    """Single-pass parse of a numeric CSV -> (float32 (rows, cols) array,
    had_header). One file read, one parse pass; quoted numeric fields OK;
    empty/ragged fields raise (matching the Python fallback's strictness)."""
    lib = _require()
    data = ctypes.POINTER(ctypes.c_float)()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    header = ctypes.c_int()
    rc = lib.dkt_csv_load(
        path.encode(), ctypes.byref(data), ctypes.byref(rows),
        ctypes.byref(cols), ctypes.byref(header),
    )
    if rc == -1:
        raise OSError(f"native csv read failed for {path!r}")
    if rc == -2:
        raise ValueError(
            f"native csv parse failed for {path!r}: malformed, empty, or "
            "ragged field"
        )
    try:
        n = rows.value * cols.value
        out = np.ctypeslib.as_array(data, shape=(n,)).copy() if n else (
            np.empty((0,), np.float32)
        )
    finally:
        lib.dkt_free(data)
    return out.reshape(rows.value, cols.value), bool(header.value)


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """dst[i] = src[idx[i]] along axis 0 for a float32 array (any rank)."""
    lib = _require()
    src = np.ascontiguousarray(src, np.float32)
    idx = np.ascontiguousarray(idx, np.int64)
    row_shape = src.shape[1:]
    row_elems = int(np.prod(row_shape)) if row_shape else 1
    out = np.empty((idx.shape[0], *row_shape), np.float32)
    lib.dkt_gather_rows_f32(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        idx.shape[0],
        row_elems,
    )
    return out
