"""Columnar in-memory Dataset — the Spark-DataFrame stand-in.

The reference's trainers consume Spark DataFrames with ``features``/``label``
columns and control distribution via ``repartition(num_workers)`` /
``coalesce(1)`` (reference: distkeras/trainers.py -> DistributedTrainer.train).
``Dataset`` reproduces that contract on host numpy arrays:

- named columns (dict of equal-length ndarrays)
- ``shuffle(seed)`` — deterministic global shuffle
  (reference: distkeras/utils.py -> shuffle)
- ``partition(num_workers)`` — deterministic contiguous split by worker index
  (the ``repartition`` analog; workers get disjoint shards)
- ``batches(batch_size)`` — minibatch assembly, the executor-side row->numpy
  loop (reference: distkeras/workers.py -> Worker minibatch assembly)

Batching drops the trailing ragged remainder so every compiled step sees one
static batch shape — a TPU/XLA requirement the Spark version didn't have.
"""

from __future__ import annotations

import numpy as np


def _take_rows(col: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Row gather behind shuffle/partition: the native C++ memcpy path for
    contiguous float32 columns (distkeras_tpu/native), numpy otherwise.
    Negative or out-of-range indices take the numpy path so semantics
    (negative wrap, IndexError) never depend on the toolchain."""
    if (
        col.dtype == np.float32
        and col.flags["C_CONTIGUOUS"]
        and idx.size > 0
        and 0 <= idx.min()
        and idx.max() < col.shape[0]
    ):
        from distkeras_tpu.data import native

        if native.available():
            return native.gather_rows(col, idx)
    return col[idx]


class Dataset:
    def __init__(self, columns: dict):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        lens = {k: len(v) for k, v in columns.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"column length mismatch: {lens}")
        self._cols = {k: np.asarray(v) for k, v in columns.items()}

    # -- basic accessors ----------------------------------------------------

    def __len__(self):
        return len(next(iter(self._cols.values())))

    @property
    def columns(self):
        return list(self._cols)

    def __getitem__(self, key):
        if isinstance(key, str):
            return self._cols[key]
        if isinstance(key, (np.ndarray, list)):
            idx = np.asarray(key)
            if idx.dtype.kind in "iu":  # row materialization (shuffle/partition)
                return Dataset(
                    {k: _take_rows(v, idx) for k, v in self._cols.items()}
                )
            return Dataset({k: v[idx] for k, v in self._cols.items()})
        if isinstance(key, slice):
            return Dataset({k: v[key] for k, v in self._cols.items()})
        raise TypeError(f"bad key {key!r}")

    def with_column(self, name, values) -> "Dataset":
        values = np.asarray(values)
        if len(values) != len(self):
            raise ValueError("column length mismatch")
        cols = dict(self._cols)
        cols[name] = values
        return Dataset(cols)

    def select(self, names) -> "Dataset":
        return Dataset({k: self._cols[k] for k in names})

    def drop(self, names) -> "Dataset":
        names = {names} if isinstance(names, str) else set(names)
        return Dataset({k: v for k, v in self._cols.items() if k not in names})

    def take(self, n: int) -> "Dataset":
        return self[: min(n, len(self))]

    def concat(self, other: "Dataset") -> "Dataset":
        if set(self.columns) != set(other.columns):
            raise ValueError("column sets differ")
        return Dataset(
            {k: np.concatenate([self._cols[k], other._cols[k]]) for k in self._cols}
        )

    # -- distribution contract ---------------------------------------------

    def shuffle(self, seed: int = 0) -> "Dataset":
        perm = np.random.default_rng(seed).permutation(len(self))
        return self[perm]

    def partition(self, num_workers: int):
        """Disjoint, near-equal contiguous shards — repartition(num_workers)."""
        idx = np.array_split(np.arange(len(self)), num_workers)
        return [self[i] for i in idx]

    def split(self, fraction: float, seed: int = 0):
        """(train, test) random split — the examples' randomSplit analog."""
        ds = self.shuffle(seed)
        n = int(len(ds) * fraction)
        return ds[:n], ds[n:]

    def batches(self, batch_size: int, columns=None, drop_remainder=True):
        """Yield dicts of ndarray minibatches with static shapes."""
        cols = columns or self.columns
        n = len(self)
        end = (n // batch_size) * batch_size if drop_remainder else n
        for i in range(0, end, batch_size):
            yield {k: self._cols[k][i : i + batch_size] for k in cols}

    def num_batches(self, batch_size: int, drop_remainder=True) -> int:
        n = len(self)
        return n // batch_size if drop_remainder else -(-n // batch_size)

    def __repr__(self):
        shapes = {k: v.shape for k, v in self._cols.items()}
        return f"Dataset(len={len(self)}, columns={shapes})"
