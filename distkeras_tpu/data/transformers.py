"""Feature-preprocessing transformers.

Mirrors the reference's transformer zoo and semantics (reference:
distkeras/transformers.py -> MinMaxTransformer, OneHotTransformer,
DenseTransformer, ReshapeTransformer, LabelIndexTransformer): each is a
driver-constructed object whose ``transform(dataset)`` appends/replaces
columns. The math runs vectorized over whole numpy columns instead of
per-row Spark closures — exactness of MinMax/OneHot is what makes accuracy
parity attributable to the optimizers, not data skew.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Transformer:
    """Base: transform(Dataset) -> Dataset."""

    def transform(self, ds: Dataset) -> Dataset:
        raise NotImplementedError

    def __call__(self, ds: Dataset) -> Dataset:
        return self.transform(ds)


class MinMaxTransformer(Transformer):
    """Rescale a numeric column from data range [o_min, o_max] ("old") to
    output range [n_min, n_max] ("new").

    Same parameterization as the reference: e.g. MNIST pixels use
    ``MinMaxTransformer(n_min=0, n_max=1, o_min=0, o_max=255)``.
    """

    def __init__(
        self,
        n_min=0.0,
        n_max=1.0,
        o_min=0.0,
        o_max=255.0,
        input_col="features",
        output_col=None,
    ):
        self.n_min, self.n_max = float(n_min), float(n_max)
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.input_col = input_col
        self.output_col = output_col or input_col

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col].astype(np.float32)
        scale = (self.n_max - self.n_min) / (self.o_max - self.o_min)
        y = (x - self.o_min) * scale + self.n_min
        return ds.with_column(self.output_col, y)


class OneHotTransformer(Transformer):
    """Integer label column -> one-hot float32 vectors of width num_classes."""

    def __init__(self, num_classes, input_col="label", output_col="label_onehot"):
        self.num_classes = int(num_classes)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, ds: Dataset) -> Dataset:
        ids = ds[self.input_col].astype(np.int64).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_classes):
            raise ValueError(
                f"labels out of range [0, {self.num_classes}): "
                f"[{ids.min()}, {ids.max()}]"
            )
        out = np.zeros((len(ids), self.num_classes), np.float32)
        out[np.arange(len(ids)), ids] = 1.0
        return ds.with_column(self.output_col, out)


class DenseTransformer(Transformer):
    """Assemble a dense float feature matrix from one or more columns.

    The reference converts sparse Spark vectors to DenseVector; here the
    analog is stacking scalar/array columns into one (N, F) float32 matrix.
    """

    def __init__(self, input_cols, output_col="features"):
        self.input_cols = (
            [input_cols] if isinstance(input_cols, str) else list(input_cols)
        )
        self.output_col = output_col

    def transform(self, ds: Dataset) -> Dataset:
        parts = []
        for c in self.input_cols:
            v = ds[c].astype(np.float32)
            parts.append(v.reshape(len(v), -1))
        return ds.with_column(self.output_col, np.concatenate(parts, axis=1))


class ReshapeTransformer(Transformer):
    """Reshape each row of a column, e.g. (784,) -> (28, 28, 1) for convnets."""

    def __init__(self, input_col, output_col, shape):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(int(d) for d in shape)

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col]
        return ds.with_column(self.output_col, x.reshape(len(x), *self.shape))


class LabelIndexTransformer(Transformer):
    """Prediction vectors -> integer class index column (argmax over classes).

    Matches the reference's use: turning predictor output into a label index
    for the evaluator (reference: distkeras/transformers.py ->
    LabelIndexTransformer feeding AccuracyEvaluator).
    """

    def __init__(self, output_dim=None, input_col="prediction", output_col="prediction_index"):
        self.output_dim = output_dim  # kept for signature parity; unused
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col]
        idx = np.argmax(x, axis=-1).astype(np.int64)
        return ds.with_column(self.output_col, idx)


class StandardScaleTransformer(Transformer):
    """(x - mean) / std per feature (Higgs pipeline).

    By default the stats are fit on the data being transformed. For a
    leak-free train/test pipeline, ``fit(train)`` first — the stored
    train statistics are then applied to every later ``transform`` (the
    held-out rows must not shape the normalization they are judged
    under)."""

    def __init__(self, input_col="features", output_col=None, epsilon=1e-8):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.epsilon = float(epsilon)
        self._mean = None
        self._std = None

    def fit(self, ds: Dataset) -> "StandardScaleTransformer":
        x = ds[self.input_col].astype(np.float32)
        self._mean = x.mean(axis=0, keepdims=True)
        self._std = x.std(axis=0, keepdims=True)
        return self

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col].astype(np.float32)
        if self._mean is None:
            mean = x.mean(axis=0, keepdims=True)
            std = x.std(axis=0, keepdims=True)
        else:
            mean, std = self._mean, self._std
        return ds.with_column(self.output_col, (x - mean) / (std + self.epsilon))
