"""Profiling, tracing, and structured metrics — the observability subsystem.

The reference has essentially none of this (SURVEY §5.1/§5.5: wall-clock
bookkeeping plus the Spark web UI; print-level logging; no structured sink).
The rebuild adds the TPU-native equivalents:

- ``trace(logdir)``: context manager around ``jax.profiler.trace`` — captures
  an XLA/xprof device profile (MXU utilization, HBM traffic, per-op timing)
  viewable in TensorBoard/Perfetto. Trainers expose it via ``profile_dir=``.
- ``annotate(name)``: named trace span (``jax.profiler.TraceAnnotation``) so
  host-side phases (pull/commit, data staging) show up in the timeline.
- ``MetricsLogger``: append-only JSONL metrics sink (thread-safe) — the
  structured-logging layer the reference lacks.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager


@contextmanager
def trace(logdir: str):
    """Capture a device profile for the enclosed block into ``logdir``."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Named span on the profiler timeline (host-side phases)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class MetricsLogger:
    """Thread-safe JSONL sink: one JSON object per line, ``ts`` added."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()

    def log(self, **fields):
        # open-append-close per record: no fd held between logs (a sweep can
        # construct thousands of trainers without leaking handles), and a
        # whole line lands per write so concurrent loggers never interleave
        record = {"ts": time.time(), **fields}
        line = json.dumps(record) + "\n"
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line)
        return record

    def close(self):
        pass  # nothing held open; kept for API compatibility

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_metrics(path: str, strict: bool = False):
    """Read a JSONL metrics file back into a list of dicts.

    A process that dies mid-append leaves a torn FINAL line; by default
    that line is dropped and every whole record before it is returned
    (``strict=True`` restores the raise). Garbage anywhere else in the
    file is still an error — a half-written tail is an expected crash
    artifact, a corrupt middle is not."""
    out = []
    held = None  # previous non-empty line: parsed only once a later
    # one proves it was not the (possibly torn) final append
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if held is not None:
                out.append(json.loads(held))
            held = line
    if held is not None:
        try:
            out.append(json.loads(held))
        except json.JSONDecodeError:
            if strict:
                raise
            # torn final append: salvage everything before it
    return out
