"""Profiling, tracing, and structured metrics — the observability subsystem.

The reference has essentially none of this (SURVEY §5.1/§5.5: wall-clock
bookkeeping plus the Spark web UI; print-level logging; no structured sink).
The rebuild adds the TPU-native equivalents:

- ``trace(logdir)``: context manager around ``jax.profiler.trace`` — captures
  an XLA/xprof device profile (MXU utilization, HBM traffic, per-op timing)
  viewable in TensorBoard/Perfetto. Trainers expose it via ``profile_dir=``.
- ``annotate(name)``: named trace span (``jax.profiler.TraceAnnotation``) so
  host-side phases (pull/commit, data staging) show up in the timeline.
- ``MetricsLogger``: append-only JSONL metrics sink (thread-safe) — the
  structured-logging layer the reference lacks.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager


@contextmanager
def trace(logdir: str):
    """Capture a device profile for the enclosed block into ``logdir``."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Named span on the profiler timeline (host-side phases)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class MetricsLogger:
    """Thread-safe JSONL sink: one JSON object per line, ``ts`` added.

    Size-bounded rotation: with ``max_bytes`` set, an append that
    would push the active file past the bound first rotates it —
    ``path`` -> ``path.1`` -> ``path.2`` -> ... up to ``keep``
    segments, the oldest dropped — so a week-long soak's sink stays
    bounded at ~``max_bytes * (keep + 1)`` instead of growing without
    limit. Rotation happens on a line boundary under the logger's
    lock, so every segment is whole-line JSONL; ``read_metrics`` reads
    across the rotated segments transparently."""

    def __init__(self, path: str, max_bytes: int | None = None,
                 keep: int = 5):
        self.path = path
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        if self.max_bytes is not None and self.max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1; got {max_bytes}")
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError(f"keep must be >= 1; got {keep}")
        self.rotations = 0
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        # a previous process that died mid-append left a torn final
        # line; appending past it would turn the expected crash
        # artifact (salvageable torn TAIL) into mid-file garbage the
        # reader rightly refuses — and rotation would archive it into
        # a strict segment. Drop the partial line now: read_metrics
        # was going to drop it anyway, and every later append (and
        # every rotated segment) stays whole-line JSONL. A concurrent
        # healthy writer always ends the file with a newline, so this
        # only ever cuts a genuinely torn tail.
        self._repair_torn_tail()

    def _repair_torn_tail(self):
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size == 0:
                    return
                f.seek(size - 1)
                if f.read(1) == b"\n":
                    return
                # scan back (bounded chunks) for the last newline
                pos = size
                keep = 0
                while pos > 0:
                    step = min(4096, pos)
                    pos -= step
                    f.seek(pos)
                    chunk = f.read(step)
                    nl = chunk.rfind(b"\n")
                    if nl != -1:
                        keep = pos + nl + 1
                        break
                f.truncate(keep)
        except OSError:
            pass  # no file yet, or unreadable: nothing to repair

    def log(self, **fields):
        # open-append-close per record: no fd held between logs (a sweep can
        # construct thousands of trainers without leaking handles), and a
        # whole line lands per write so concurrent loggers never interleave
        record = {"ts": time.time(), **fields}
        line = json.dumps(record) + "\n"
        with self._lock:
            if self.max_bytes is not None:
                try:
                    size = os.path.getsize(self.path)
                except OSError:
                    size = 0
                if size > 0 and size + len(line) > self.max_bytes:
                    self._rotate_locked()
            with open(self.path, "a") as f:
                f.write(line)
        return record

    def _rotate_locked(self):
        """Shift ``path.i`` -> ``path.i+1`` (the oldest, ``path.keep``,
        is dropped), then ``path`` -> ``path.1``. Caller holds the
        lock; every move is an atomic rename."""
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self.rotations += 1

    def close(self):
        pass  # nothing held open; kept for API compatibility

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def rotated_segments(path: str) -> list:
    """Every on-disk segment of a (possibly rotated) JSONL sink,
    OLDEST FIRST: ``path.N`` ... ``path.1``, then the active
    ``path`` — so concatenating the reads preserves append order."""
    out = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        out.append(f"{path}.{n}")
        n += 1
    out.reverse()
    if os.path.exists(path) or not out:
        out.append(path)  # missing active file raises in the reader
    return out


def read_metrics(path: str, strict: bool = False):
    """Read a JSONL metrics file back into a list of dicts — across
    rotated segments (``MetricsLogger(max_bytes=...)`` writes
    ``path.N`` ... ``path.1`` plus the active ``path``; records come
    back oldest first, exactly as appended).

    A process that dies mid-append leaves a torn FINAL line of the
    ACTIVE file; by default that line is dropped and every whole
    record before it is returned (``strict=True`` restores the
    raise). Garbage anywhere else — mid-file, or in a rotated segment
    (which only ever holds whole lines, because rotation happens on a
    line boundary) — is still an error: a half-written tail is an
    expected crash artifact, a corrupt middle is not."""
    segments = rotated_segments(path)
    out = []
    for seg in segments[:-1]:
        out.extend(_read_segment(seg, salvage=False))
    out.extend(_read_segment(segments[-1], salvage=not strict))
    return out


def _read_segment(path: str, salvage: bool):
    out = []
    held = None  # previous non-empty line: parsed only once a later
    # one proves it was not the (possibly torn) final append
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if held is not None:
                out.append(json.loads(held))
            held = line
    if held is not None:
        try:
            out.append(json.loads(held))
        except json.JSONDecodeError:
            if not salvage:
                raise
            # torn final append: salvage everything before it
    return out
