"""Model and parameter (de)serialization.

TPU-native equivalent of the reference's model wire format (reference:
distkeras/utils.py -> serialize_keras_model / deserialize_keras_model, which
ship a dict of {architecture-JSON, weight list} between driver and executors).

Here a model is (spec, params): the architecture is a declarative layer-spec
list (JSON-able), and the parameters are a pytree of arrays. The wire format
is a dict {"spec": <json str>, "weights": <flat list of ndarrays>} — the same
split the reference uses, so models survive process/network boundaries without
pickling code objects.
"""

from __future__ import annotations

import io
import json
import pickle

import jax
import numpy as np


def serialize_params(params) -> bytes:
    """Pytree of arrays -> bytes (treedef-json + npz payload, no pickled code)."""
    leaves, treedef = jax.tree.flatten(params)
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(leaf) for leaf in leaves])
    return pickle.dumps({"treedef": treedef, "npz": buf.getvalue()})


def deserialize_params(blob: bytes):
    payload = pickle.loads(blob)
    with np.load(io.BytesIO(payload["npz"])) as z:
        leaves = [z[k] for k in z.files]
    return jax.tree.unflatten(payload["treedef"], leaves)


def serialize_model(model) -> bytes:
    """Sequential model -> bytes: architecture spec JSON + weight arrays."""
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(w) for w in model.get_weights()])
    return pickle.dumps(
        {
            "spec": json.dumps(model.get_config()),
            "input_shape": model.input_shape,
            "weights": buf.getvalue(),
        }
    )


def deserialize_model(blob: bytes):
    from distkeras_tpu.models.sequential import Sequential

    payload = pickle.loads(blob)
    model = Sequential.from_config(json.loads(payload["spec"]))
    model.build(payload["input_shape"])
    with np.load(io.BytesIO(payload["weights"])) as z:
        model.set_weights([z[k] for k in z.files])
    return model


def save_params(path: str, params) -> None:
    with open(path, "wb") as f:
        f.write(serialize_params(params))


def load_params(path: str):
    with open(path, "rb") as f:
        return deserialize_params(f.read())
