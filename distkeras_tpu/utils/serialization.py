"""Model and parameter (de)serialization — pickle-free.

TPU-native equivalent of the reference's model wire format (reference:
distkeras/utils.py -> serialize_keras_model / deserialize_keras_model, which
ship a dict of {architecture-JSON, weight list} between driver and executors).
The reference pickles those dicts onto the socket; unpickling peer bytes is
arbitrary-code-execution on the receiving host, so this codec replaces it
with a non-executable encoding (VERDICT r1 weak #3 / next-step 6):

    frame   = MAGIC "DKT1" + 4-byte big-endian header length
            + JSON header + raw npz payload
    header  = {"tree": <structure node>} — a typed description of the pytree
              (dict / list / tuple / namedtuple / None nodes, leaf indices)
    payload = np.savez of the numeric leaves, loaded with allow_pickle=False

NamedTuple nodes (optax optimizer states) are encoded structurally by class
path + field names. On decode the class is re-imported ONLY when its module
root is on a small allowlist and the imported object really is a NamedTuple
class with the same fields; anything else degrades to an anonymous namedtuple
with the same fields — structurally equal for compute, never an arbitrary
constructor call.
"""

from __future__ import annotations

import collections
import importlib
import io
import json
import struct

import numpy as np

_MAGIC = b"DKT1"
_HLEN = struct.Struct(">I")

# Module roots we are willing to import while decoding a namedtuple node.
_NT_MODULE_ALLOWLIST = ("optax", "distkeras_tpu", "jax", "flax", "collections")


# ------------------------------------------------------------ structure codec


def _encode_node(obj, leaves: list) -> dict:
    from distkeras_tpu.ops.quantization import Int4Weight

    if obj is None:
        return {"t": "none"}
    if isinstance(obj, Int4Weight):
        # packed int4 weight (serving bundles): the two array children
        # ride the leaf stream like any other; the logical row count is
        # structural metadata
        return {
            "t": "int4",
            "rows": int(obj.rows),
            "children": [
                _encode_node(obj.q4, leaves),
                _encode_node(obj.s, leaves),
            ],
        }
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        cls = type(obj)
        return {
            "t": "nt",
            "cls": f"{cls.__module__}:{cls.__qualname__}",
            "fields": list(obj._fields),
            "children": [_encode_node(c, leaves) for c in obj],
        }
    if isinstance(obj, dict):
        keys = list(obj.keys())
        if not all(isinstance(k, str) for k in keys):
            raise TypeError("only str-keyed dicts are serializable")
        return {
            "t": "dict",
            "keys": keys,
            "children": [_encode_node(obj[k], leaves) for k in keys],
        }
    if isinstance(obj, (list, tuple)):
        return {
            "t": "list" if isinstance(obj, list) else "tuple",
            "children": [_encode_node(c, leaves) for c in obj],
        }
    arr = np.asarray(obj)
    if arr.dtype.kind not in "biufc":
        raise TypeError(f"non-numeric leaf of dtype {arr.dtype} is not serializable")
    leaves.append(arr)
    return {"t": "leaf", "i": len(leaves) - 1}


def _resolve_namedtuple(path: str, fields: list):
    """Import the namedtuple class at ``module:qualname`` if (and only if)
    it is allowlisted and structurally matches; else build an anonymous
    stand-in with the same fields."""
    mod_name, _, qual = str(path).partition(":")
    if mod_name.split(".")[0] in _NT_MODULE_ALLOWLIST:
        try:
            obj = importlib.import_module(mod_name)
            for part in qual.split("."):
                obj = getattr(obj, part)
            if (
                isinstance(obj, type)
                and issubclass(obj, tuple)
                and getattr(obj, "_fields", None) == tuple(fields)
            ):
                return obj
        except Exception:
            pass
    name = qual.rsplit(".", 1)[-1] or "AnonymousState"
    if not name.isidentifier():
        name = "AnonymousState"
    return collections.namedtuple(name, fields, rename=True)


def _decode_node(node: dict, leaves: list):
    kind = node["t"]
    if kind == "none":
        return None
    if kind == "leaf":
        return leaves[node["i"]]
    children = [_decode_node(c, leaves) for c in node["children"]]
    if kind == "int4":
        from distkeras_tpu.ops.quantization import Int4Weight

        return Int4Weight(children[0], children[1], int(node["rows"]))
    if kind == "dict":
        return dict(zip(node["keys"], children))
    if kind == "list":
        return children
    if kind == "tuple":
        return tuple(children)
    if kind == "nt":
        cls = _resolve_namedtuple(node["cls"], list(node["fields"]))
        return cls(*children)
    raise ValueError(f"unknown structure node type {kind!r}")


# -------------------------------------------------------------------- framing


def pack_frame(header: dict, blob: bytes = b"") -> bytes:
    """JSON header + raw binary payload in one length-framed buffer."""
    h = json.dumps(header).encode()
    return _MAGIC + _HLEN.pack(len(h)) + h + blob


def unpack_frame(data: bytes) -> tuple[dict, bytes]:
    if data[: len(_MAGIC)] != _MAGIC:
        raise ValueError("bad frame: missing DKT1 magic (refusing legacy pickle)")
    off = len(_MAGIC)
    (hlen,) = _HLEN.unpack_from(data, off)
    off += _HLEN.size
    header = json.loads(data[off : off + hlen].decode())
    return header, data[off + hlen :]


# ----------------------------------------------------------------- public API


def serialize_params(params) -> bytes:
    """Pytree of arrays -> bytes (typed structure header + npz, no pickle)."""
    leaves: list = []
    tree = _encode_node(params, leaves)
    buf = io.BytesIO()
    np.savez(buf, **{f"a{i}": leaf for i, leaf in enumerate(leaves)})
    return pack_frame({"tree": tree}, buf.getvalue())


def deserialize_params(blob: bytes):
    header, payload = unpack_frame(blob)
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        leaves = [z[f"a{i}"] for i in range(len(z.files))]
    return _decode_node(header["tree"], leaves)


def serialize_model(model) -> bytes:
    """Sequential model -> bytes: architecture spec JSON + weight arrays."""
    from distkeras_tpu.ops.quantization import count_quantized

    if count_quantized(getattr(model, "params", None) or {}):
        raise ValueError(
            "model holds an int8-quantized serving tree; quantization is a "
            "LOAD-TIME transform — serialize the f32 master and call "
            "ops.quantization.quantize_model after deserialize_model"
        )
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(w) for w in model.get_weights()])
    return pack_frame(
        {
            "spec": json.dumps(model.get_config()),
            "input_shape": list(model.input_shape),
        },
        buf.getvalue(),
    )


def deserialize_model(blob: bytes):
    from distkeras_tpu.models.sequential import Sequential

    header, payload = unpack_frame(blob)
    if header.get("serving"):
        raise ValueError(
            "this frame is a quantized SERVING bundle, not an f32 "
            "model — load it with deserialize_serving_bundle / "
            "load_serving_bundle"
        )
    model = Sequential.from_config(json.loads(header["spec"]))
    model.build(tuple(header["input_shape"]))
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        model.set_weights([z[k] for k in z.files])
    return model


def save_params(path: str, params) -> None:
    with open(path, "wb") as f:
        f.write(serialize_params(params))


def load_params(path: str):
    with open(path, "rb") as f:
        return deserialize_params(f.read())


# ------------------------------------------------------------ serving bundles


def serialize_serving_bundle(model) -> bytes:
    """Quantized model -> bytes, the DELIBERATE counterpart of
    ``serialize_model``'s quantized-tree rejection: that guard stops a
    lossy tree being saved AS the training master by accident; this
    format exists so serving hosts don't ship 4-8x the weight bytes and
    re-quantize on every boot. The frame carries the architecture spec
    plus the quantized params tree (int8 dicts ride the leaf stream
    natively; ``Int4Weight`` has a structural node). Loads serve-only:
    trainers and ``serialize_model`` reject the result, exactly as they
    reject any quantized tree."""
    from distkeras_tpu.ops.quantization import count_quantized

    if getattr(model, "params", None) is None:
        raise ValueError("serving bundle needs a BUILT model")
    if not count_quantized(model.params):
        raise ValueError(
            "model is not quantized — a serving bundle stores the "
            "quantized tree (ops.quantization.quantize_model first); "
            "for the f32 master use serialize_model"
        )
    return pack_frame(
        {
            "spec": json.dumps(model.get_config()),
            "input_shape": list(model.input_shape),
            "serving": True,
        },
        serialize_params(model.params),
    )


def deserialize_serving_bundle(blob: bytes):
    """bytes -> a serve-only model: architecture rebuilt from the spec,
    params replaced by the stored quantized tree (validated structurally
    against the spec-built model — same tree paths, quantized leaves'
    logical shapes matching the f32 ones they replace)."""
    from distkeras_tpu.models.sequential import Sequential
    from distkeras_tpu.ops.quantization import is_quantized, qshape

    header, payload = unpack_frame(blob)
    if not header.get("serving"):
        raise ValueError(
            "not a serving bundle (use deserialize_model for f32 frames)"
        )
    model = Sequential.from_config(json.loads(header["spec"]))
    model.build(tuple(header["input_shape"]))
    loaded = deserialize_params(payload)

    def check(path, built, got):
        if is_quantized(got):
            # validate the quantized leaf's INTERNALS, not just its
            # logical shape: a truncated q4 or a broadcastable (1,)
            # scale would otherwise load cleanly and serve garbage
            # (qshape trusts Int4Weight.rows; broadcasting hides a
            # wrong-length s until the predictions are silently wrong)
            from distkeras_tpu.ops.quantization import Int4Weight

            want = tuple(np.shape(built))
            if len(want) != 2:
                # quantization only ever replaces 2-D matmul weights; a
                # "quantized" leaf standing in for a bias/LN gain is a
                # crafted payload and must fail as a ValueError, not an
                # IndexError on want[1] below
                raise ValueError(
                    f"serving bundle structure mismatch at {path}: "
                    f"quantized leaf where the spec builds a "
                    f"{len(want)}-D array"
                )
            if tuple(qshape(got)) != want:
                raise ValueError(
                    f"serving bundle shape mismatch at {path}: "
                    f"spec builds {want}, bundle holds {tuple(qshape(got))}"
                )
            if isinstance(got, Int4Weight):
                q4_want = ((want[0] + 1) // 2, want[1])
                if (
                    tuple(np.shape(got.q4)) != q4_want
                    or tuple(np.shape(got.s)) != (want[1],)
                    or np.asarray(got.q4).dtype != np.int8
                    or np.asarray(got.s).dtype != np.float32
                ):
                    raise ValueError(
                        f"serving bundle int4 internals mismatch at "
                        f"{path}: q4 {tuple(np.shape(got.q4))}/"
                        f"{np.asarray(got.q4).dtype} vs {q4_want}/int8, "
                        f"s {tuple(np.shape(got.s))}/"
                        f"{np.asarray(got.s).dtype} vs ({want[1]},)/f32"
                    )
            # int8: qshape already IS q.shape, so only the scale vector
            # and the dtypes need their own checks (a broadcastable (1,)
            # scale serves silently wrong numbers; an int32 "q" — or an
            # int32 q4 above, whose nibble sign-extension returns the
            # whole packed byte — decodes to garbage with no error)
            elif (
                tuple(np.shape(got["s"])) != (want[1],)
                or np.asarray(got["q"]).dtype != np.int8
                or np.asarray(got["s"]).dtype != np.float32
            ):
                raise ValueError(
                    f"serving bundle int8 internals mismatch at {path}: "
                    f"q dtype {np.asarray(got['q']).dtype} vs int8, "
                    f"s {tuple(np.shape(got['s']))}/"
                    f"{np.asarray(got['s']).dtype} vs ({want[1]},)/f32"
                )
            return
        if isinstance(built, dict) != isinstance(got, dict) or (
            isinstance(built, dict) and set(built) != set(got)
        ):
            raise ValueError(
                f"serving bundle structure mismatch at {path}"
            )
        if isinstance(built, dict):
            for k in built:
                check(f"{path}/{k}", built[k], got[k])
        elif isinstance(built, (list, tuple)):
            if len(built) != len(got):
                raise ValueError(
                    f"serving bundle structure mismatch at {path}"
                )
            for i, (b, g) in enumerate(zip(built, got)):
                check(f"{path}[{i}]", b, g)
        elif np.shape(built) != np.shape(got):
            raise ValueError(
                f"serving bundle shape mismatch at {path}: "
                f"{np.shape(built)} vs {np.shape(got)}"
            )
        elif np.asarray(built).dtype != np.asarray(got).dtype:
            # shape alone would let a crafted bundle substitute e.g. a
            # float64 or int array for an f32 bias/LN gain and serve it
            # silently; non-quantized leaves must match the spec-built
            # dtype exactly (the quantized branch pins its own dtypes)
            raise ValueError(
                f"serving bundle dtype mismatch at {path}: spec builds "
                f"{np.asarray(built).dtype}, bundle holds "
                f"{np.asarray(got).dtype}"
            )

    check("params", model.params, loaded)
    model.params = loaded
    return model


def save_serving_bundle(path: str, model) -> None:
    with open(path, "wb") as f:
        f.write(serialize_serving_bundle(model))


def load_serving_bundle(path: str):
    with open(path, "rb") as f:
        return deserialize_serving_bundle(f.read())
