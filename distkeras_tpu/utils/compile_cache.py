"""Persistent XLA compilation cache.

The reference pays its per-executor startup cost in Keras model
deserialization + TF graph construction (reference: distkeras/workers.py ->
Worker.prepare_model, re-run in every Spark task). The TPU-shaped analog of
that cost is XLA compilation (~20-40s per program on a v5e), and the
TPU-shaped fix is the persistent compilation cache: compiled executables are
keyed by HLO hash on disk, so re-creating a trainer (new jit closures, same
program) or re-running a harness hits the cache instead of the compiler.

Used by bench.py / benchmarks.py / tests/conftest.py; call before the first
compilation (any time after import works — the cache is consulted per
compile).
"""

from __future__ import annotations

import os
import tempfile


def _default_dir() -> str:
    # user-scoped: a fixed world-shared /tmp name would collide (and be
    # plantable) on multi-user hosts
    uid = os.getuid() if hasattr(os, "getuid") else "u"
    return os.path.join(tempfile.gettempdir(), f"dkt_jax_cache_{uid}")


def enable_compile_cache(path: str | None = None, platform: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing). Returns the cache directory, or None when skipped. Safe to
    call repeatedly.

    ``platform``: the resolved backend name, or None to ask JAX (which
    initializes the backend). The cache is skipped for "cpu": XLA:CPU AOT
    entries embed compile-machine feature lists that warn (and can SIGILL)
    on reload, and CPU compiles of these programs are seconds, not the
    20-40s a TPU compile costs."""
    import jax

    if platform is None:
        platform = jax.default_backend()
    if platform == "cpu":
        return None

    path = path or os.environ.get("DKT_COMPILE_CACHE") or _default_dir()
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every program that takes meaningful compile time; the default
    # threshold (1s+) skips the small-but-numerous ragged-window variants
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    return path
