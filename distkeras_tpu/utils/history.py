"""Training history & wall-clock bookkeeping.

Parity with the reference's Trainer bookkeeping (reference:
distkeras/trainers.py -> Trainer.record_training_start/record_training_end/
get_training_time/get_history): per-worker batch histories plus start/stop
wall-clock timing.
"""

from __future__ import annotations

import time
from collections import defaultdict


class TrainingHistory:
    """Accumulates per-step metrics, per worker, plus wall-clock timing."""

    def __init__(self):
        self._records = defaultdict(list)  # worker_id -> list of dict
        self._windows = defaultdict(list)  # worker_id -> list of (samples, sec)
        self._validation = []  # per-epoch val_* metric dicts
        self._t_start = None
        self._t_end = None

    def record_training_start(self):
        self._t_start = time.time()

    def record_training_end(self):
        self._t_end = time.time()

    def get_training_time(self) -> float:
        if self._t_start is None:
            return 0.0
        end = self._t_end if self._t_end is not None else time.time()
        return end - self._t_start

    def append(self, worker_id: int, **metrics):
        self._records[worker_id].append(
            {k: float(v) for k, v in metrics.items()}
        )

    def extend(self, worker_id: int, records):
        for r in records:
            self.append(worker_id, **r)

    def get_history(self, worker_id=None):
        if worker_id is not None:
            return list(self._records[worker_id])
        merged = []
        for wid in sorted(self._records):
            merged.extend(self._records[wid])
        return merged

    def num_updates(self) -> int:
        return sum(len(v) for v in self._records.values())

    # -- per-epoch validation (Keras-style val_* metrics) -------------------

    def record_validation(self, epoch: int, metrics: dict):
        self._validation.append(
            {"epoch": int(epoch), **{k: float(v) for k, v in metrics.items()}}
        )

    def get_validation_history(self):
        return list(self._validation)

    # -- throughput bookkeeping (profiling subsystem; absent upstream) ------

    def record_window(self, worker_id: int, samples: int, seconds: float):
        """One dispatched window: how many samples, how long (wall)."""
        self._windows[worker_id].append((int(samples), float(seconds)))

    def get_timings(self, worker_id=None):
        if worker_id is not None:
            return list(self._windows[worker_id])
        merged = []
        for wid in sorted(self._windows):
            merged.extend(self._windows[wid])
        return merged

    def total_samples(self) -> int:
        return sum(s for s, _ in self.get_timings())

    def samples_per_second(self) -> float:
        """Aggregate throughput: total samples / total wall time. Windows
        overlap across workers (async), so wall time, not summed window time,
        is the honest denominator."""
        t = self.get_training_time()
        return self.total_samples() / t if t > 0 else 0.0

    def averages(self) -> dict:
        merged = self.get_history()
        if not merged:
            return {}
        keys = merged[-1].keys()
        return {
            k: sum(r[k] for r in merged if k in r)
            / max(1, sum(1 for r in merged if k in r))
            for k in keys
        }
