"""Pytree arithmetic helpers.

The async parameter-server algorithms (reference:
distkeras/parameter_servers.py -> DeltaParameterServer.handle_commit and
distkeras/workers.py per-algorithm delta rules) operate on "weight lists".
Here the model parameters are an arbitrary JAX pytree, so every delta rule is
expressed through these pure, jit-friendly tree ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def tree_add(a, b):
    """a + b, leaf-wise."""
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a, b):
    """a - b, leaf-wise."""
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(a, s):
    """s * a for scalar s, leaf-wise."""
    return jax.tree.map(lambda x: x * s, a)


def tree_zeros_like(a):
    return jax.tree.map(jnp.zeros_like, a)


def tree_mean(trees):
    """Element-wise mean of a list of pytrees (AveragingTrainer's merge rule)."""
    n = len(trees)
    if n == 0:
        raise ValueError("tree_mean of empty list")
    acc = trees[0]
    for t in trees[1:]:
        acc = tree_add(acc, t)
    return tree_scale(acc, 1.0 / n)


def tree_dot(a, b):
    """Sum of element-wise products across all leaves (scalar)."""
    parts = jax.tree.leaves(jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b))
    return sum(parts)


def tree_norm(a):
    """Global L2 norm across all leaves."""
    return jnp.sqrt(
        sum(jax.tree.leaves(jax.tree.map(lambda x: jnp.vdot(x, x), a, a)))
    )


def host_copy(a):
    """Forced copy of every leaf to host numpy.

    The compiled window functions donate their params/state/opt-state input
    buffers (HBM double-buffering); callers seed those loops with owned host
    copies so donation can never consume an array something else still
    references (np.array(, copy=True) — np.asarray may alias on CPU).
    """
    return jax.tree.map(lambda x: np.array(x, copy=True), a)


def tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    """Host-side structural + numerical equality check (for tests)."""
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    return all(
        np.allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol)
        for x, y in zip(la, lb)
    )
