"""Checkpoint / resume subsystem.

The reference has NO checkpointing (reference: distkeras/parameter_servers.py
-> ParameterServer holds weights only in memory; weights surface once, at
``train()`` end — SURVEY §5.4), so mid-training failure loses everything.
This module is the compensating addition the rebuild requires:

- ``Checkpointer``: step-numbered, atomic, retained-N on-disk snapshots of
  named pytrees plus a JSON metadata dict. Directory layout::

      <dir>/ckpt_0000000042/
          params.tree     (treedef + npz, via utils.serialization)
          opt_state.tree
          meta.json

  Writes land in a temp dir first and are published with ``os.replace`` so a
  crash mid-save never leaves a readable-but-corrupt checkpoint.

- Trainer integration (see trainers.py): epoch-granular snapshots for
  SingleTrainer / SynchronousDistributedTrainer (params, state, opt_state,
  rng — resume is bit-identical to an uninterrupted run), and PS-update-
  granular snapshots for the async PS trainers: center + PS meta (DynSGD's
  staleness version counter and the exactly-once dedup table) + each
  worker's latest committed local state (elastic replica params, model
  state, optimizer moments, rng, commit seq). An async resume therefore
  restores a reachable configuration of the whole async system; workers
  skip the windows the restored center already absorbed and continue their
  replicas rather than re-adopting the center.
"""

from __future__ import annotations

import json
import os
import shutil
import threading

import jax
import numpy as np

from distkeras_tpu.utils.serialization import deserialize_params, serialize_params

_PREFIX = "ckpt_"
_WIDTH = 10


class Checkpointer:
    """Atomic on-disk checkpoints of named pytrees + JSON metadata."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = int(max_to_keep)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()

    # -- paths ---------------------------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_PREFIX}{step:0{_WIDTH}d}")

    def all_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_PREFIX):
                try:
                    steps.append(int(name[len(_PREFIX) :]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save / restore ------------------------------------------------------

    def save(
        self,
        step: int,
        trees: dict | None = None,
        meta: dict | None = None,
        overwrite: bool = False,
    ):
        """Write checkpoint ``step``. Returns False if it already exists
        (concurrent committers may race to the same step; first wins).
        ``overwrite=True`` replaces an existing step instead — for the
        end-of-run save, whose payload supersedes a same-numbered periodic
        snapshot (fresher worker states, identical center)."""
        step = int(step)
        final = self._step_dir(step)
        with self._lock:
            if os.path.exists(final) and not overwrite:
                return False
            tmp = os.path.join(
                self.directory, f".tmp_{step}_{os.getpid()}_{threading.get_ident()}"
            )
            os.makedirs(tmp, exist_ok=True)
            try:
                # serialize FULLY into tmp before touching an existing
                # checkpoint: a failure here must never destroy a prior
                # valid step (the old dir is removed only once the
                # replacement is completely on disk)
                for name, tree in (trees or {}).items():
                    host = jax.tree.map(np.asarray, tree)
                    with open(os.path.join(tmp, f"{name}.tree"), "wb") as f:
                        f.write(serialize_params(host))
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump(meta or {}, f)
                if os.path.exists(final):
                    if not overwrite:
                        # cross-process race (the threading lock is
                        # per-process): another committer won — keep
                        # first-wins instead of clobbering its checkpoint
                        return False
                    # overwrite is the end-of-run single-writer path; the
                    # replacement is already fully serialized in tmp
                    shutil.rmtree(final, ignore_errors=True)
                try:
                    os.replace(tmp, final)
                except OSError:
                    if overwrite:
                        raise
                    # exists() -> replace() is not atomic: a concurrent
                    # committer can publish in between, and replace onto a
                    # non-empty dir raises — that is the same first-wins
                    # outcome, reported the same way
                    return False
            finally:
                if os.path.isdir(tmp):
                    shutil.rmtree(tmp, ignore_errors=True)
            self._retain()
        return True

    def restore(self, step: int | None = None):
        """Return ``(step, trees, meta)`` for ``step`` (default: latest).
        Raises FileNotFoundError if there is nothing to restore."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        d = self._step_dir(int(step))
        if not os.path.isdir(d):
            raise FileNotFoundError(d)
        trees = {}
        for name in sorted(os.listdir(d)):
            if name.endswith(".tree"):
                with open(os.path.join(d, name), "rb") as f:
                    trees[name[: -len(".tree")]] = deserialize_params(f.read())
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        return int(step), trees, meta

    def _retain(self):
        steps = self.all_steps()
        for step in steps[: -self.max_to_keep] if self.max_to_keep > 0 else []:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
