"""Core utilities: pytree math, serialization, RNG, training history.

TPU-native replacement for the reference's ``distkeras/utils.py``
(serialize_keras_model / deserialize_keras_model / shuffle / row helpers).
"""

from distkeras_tpu.utils.tree import (
    tree_add,
    tree_sub,
    tree_scale,
    tree_zeros_like,
    tree_mean,
    tree_dot,
    tree_norm,
    tree_allclose,
)
from distkeras_tpu.utils.serialization import (
    serialize_model,
    deserialize_model,
    serialize_params,
    deserialize_params,
    save_params,
    load_params,
    serialize_serving_bundle,
    deserialize_serving_bundle,
    save_serving_bundle,
    load_serving_bundle,
)
from distkeras_tpu.utils.compile_cache import enable_compile_cache
from distkeras_tpu.utils.history import TrainingHistory
from distkeras_tpu.utils.rng import RngSeq
from distkeras_tpu.utils.checkpoint import Checkpointer
