"""Int8 delta compression with error feedback — the async DCN path's
bandwidth tier.

The reference ships full pickled float32 weight sets on every pull/commit
(reference: distkeras/networking.py -> send_data/recv_data; SURVEY §5.8:
"no compression"); its scalability ceiling is the driver link. Here the
async algorithms' COMMIT payloads (gradient deltas, elastic
displacements) can ride the wire as int8: per-leaf symmetric linear
quantization (scale = max|x| / 127) cuts commit bytes ~4x, and the worker
keeps the quantization error as a residual added to its NEXT delta
(error feedback, a la 1-bit SGD / EF-SGD) so the error is carried, not
lost — cumulative drift stays bounded by one quantization step instead of
growing with the step count.

Wire format: ``{"__dkt_q8__": {"q": int8 tree, "s": float32 scale tree}}``
— plain arrays, so the pickle-free DKT1 frame (utils/serialization)
carries it unchanged. ``ParameterServer.commit`` transparently
dequantizes, so every PS rule (Delta/ADAG/DynSGD) and both transports
(in-process, socket/DCN) work with compression on.
"""

from __future__ import annotations

import jax
import numpy as np

Q8_KEY = "__dkt_q8__"


def _quantize_leaf(a):
    a = np.asarray(a, np.float32)
    amax = np.max(np.abs(a)) if a.size else np.float32(0)
    if not np.isfinite(amax):
        # a NaN/Inf delta means the worker diverged; quantizing it would
        # poison the error-feedback residual for every later commit
        # (np.round(nan) -> undefined int8), so fail loudly at the commit
        # boundary instead (ADVICE r3 #3)
        raise FloatingPointError(
            "non-finite delta leaf (max|x| = %r): refusing to quantize a "
            "diverged update" % amax
        )
    scale = np.float32(amax / 127.0)
    if scale == 0.0:
        return np.zeros(a.shape, np.int8), scale
    return np.clip(np.round(a / scale), -127, 127).astype(np.int8), scale


def _dequantize_leaf(q, scale):
    return q.astype(np.float32) * np.float32(scale)


def quantize_tree(tree):
    """-> (payload, dequantized tree). The payload is what goes on the
    wire; the dequantized tree is what the PS will reconstruct (callers
    use it to compute the error-feedback residual without a round trip)."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    pairs = [_quantize_leaf(a) for a in flat]
    unflat = jax.tree_util.tree_unflatten
    qs = unflat(treedef, [q for q, _ in pairs])
    ss = unflat(treedef, [s for _, s in pairs])
    deq = jax.tree.map(_dequantize_leaf, qs, ss)
    return {Q8_KEY: {"q": qs, "s": ss}}, deq


def dequantize_tree(payload):
    body = payload[Q8_KEY]
    return jax.tree.map(_dequantize_leaf, body["q"], body["s"])


def is_compressed(delta) -> bool:
    return isinstance(delta, dict) and set(delta.keys()) == {Q8_KEY}


def maybe_decompress(delta):
    """PS-side entry: pass raw deltas through, reconstruct compressed ones
    (int8-quantized or top-k-sparsified)."""
    if is_compressed(delta):
        return dequantize_tree(delta)
    if is_topk(delta):
        return topk_decompress(delta)
    return delta


BF16_KEY = "__dkt_bf16__"


def _bf16_encode_leaf(a):
    a = np.asarray(a)
    if a.dtype != np.float32:
        return a, np.int8(0)  # non-f32 leaves ride raw (flagged)
    u = a.view(np.uint32)
    # round-to-nearest-even on the truncated mantissa — EXCEPT for
    # exponent 0xFF lanes (inf/NaN): the rounding add would carry through
    # the exponent and turn a NaN center into inf (or wrap to 0.0),
    # silently masking a diverged run; truncation preserves the payload
    rounded = (u + np.uint32(0x7FFF) + ((u >> 16) & np.uint32(1))) >> 16
    nonfinite = (u & np.uint32(0x7F800000)) == np.uint32(0x7F800000)
    out = np.where(nonfinite, u >> 16, rounded)
    # a NaN whose payload lives only in the truncated bits must stay NaN
    out = np.where(
        nonfinite & ((u & np.uint32(0x007FFFFF)) != 0),
        out | np.uint32(0x0040),
        out,
    )
    return out.astype(np.uint16), np.int8(1)


def _bf16_decode_leaf(v, flag):
    if not int(flag):
        return v
    return (v.astype(np.uint32) << 16).view(np.float32)


def bf16_encode_tree(tree):
    """Truncate float32 leaves to bfloat16-on-the-wire (uint16 payload,
    round-to-nearest); non-f32 leaves pass through, flagged. Halves pull
    bytes at bf16's 8-bit-mantissa precision — the same precision the
    compute path already runs activations at (compute_dtype)."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    pairs = [_bf16_encode_leaf(a) for a in flat]
    unflat = jax.tree_util.tree_unflatten
    return {
        BF16_KEY: {
            "v": unflat(treedef, [v for v, _ in pairs]),
            "m": unflat(treedef, [m for _, m in pairs]),
        }
    }


def bf16_decode_tree(payload):
    body = payload[BF16_KEY]
    return jax.tree.map(_bf16_decode_leaf, body["v"], body["m"])


def is_bf16(tree) -> bool:
    return isinstance(tree, dict) and set(tree.keys()) == {BF16_KEY}


# ------------------------------------------------------- int8 pull tier

PULL_Q8_KEY = "__dkt_pull_q8__"

#: the values DistributedTrainer and ParameterServer accept for
#: pull_compress — ONE tuple so the two validation sites cannot drift
PULL_COMPRESS_VALUES = (None, "bfloat16", "int8")


def validate_pull_compress(spec):
    if spec not in PULL_COMPRESS_VALUES:
        raise ValueError(
            f"pull_compress must be one of {PULL_COMPRESS_VALUES}; got "
            f"{spec!r}"
        )
    return spec


def _int8_encode_leaf(a):
    """f32 leaf -> (int8 payload, scale, flag=1); anything the tier cannot
    represent faithfully rides raw with flag=0 — non-f32 leaves (integer/
    bool params are preserved by design, same as the bf16 tier) AND
    non-finite leaves (a NaN center must reach the worker AS NaN so a
    diverged run surfaces instead of killing the PS connection thread)."""
    a = np.asarray(a)
    if a.dtype != np.float32:
        return a, np.float32(0), np.int8(0)
    amax = np.max(np.abs(a)) if a.size else np.float32(0)
    if not np.isfinite(amax):
        return a, np.float32(0), np.int8(0)
    scale = np.float32(amax / 127.0)
    if scale == 0.0:
        return np.zeros(a.shape, np.int8), scale, np.int8(1)
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return q, scale, np.int8(1)


def _int8_decode_leaf(v, s, flag):
    if not int(flag):
        return v
    return v.astype(np.float32) * np.float32(s)


def int8_encode_tree(tree):
    """Per-tensor symmetric int8 for the PULL direction: ~4x fewer center
    bytes than f32. One-shot rounding (error <= max|w|/254 per weight) —
    no error feedback exists on pulls because nothing accumulates; the
    async algorithms' noise tolerance absorbs it (convergence pinned by
    tests/test_compression.py). Unlike the commit-side quantize_tree this
    builds no dequantized copy (pulls don't need a residual) and passes
    non-f32 / non-finite leaves through raw, flagged."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    triples = [_int8_encode_leaf(a) for a in flat]
    unflat = jax.tree_util.tree_unflatten
    return {
        PULL_Q8_KEY: {
            "v": unflat(treedef, [v for v, _, _ in triples]),
            "s": unflat(treedef, [s for _, s, _ in triples]),
            "f": unflat(treedef, [f for _, _, f in triples]),
        }
    }


def int8_decode_tree(payload):
    body = payload[PULL_Q8_KEY]
    return jax.tree.map(_int8_decode_leaf, body["v"], body["s"], body["f"])


def is_pull_q8(tree) -> bool:
    return isinstance(tree, dict) and set(tree.keys()) == {PULL_Q8_KEY}


def maybe_decode_pull(center):
    """Worker-side entry: reconstruct a bf16- or int8-encoded pulled
    center (the PS encodes per its pull_compress; both wire forms are
    self-describing)."""
    if is_bf16(center):
        return bf16_decode_tree(center)
    if is_pull_q8(center):
        return int8_decode_tree(center)
    return center


def compress_with_feedback(delta, residual):
    """Worker-side entry: fold the previous residual into this delta,
    quantize, and return (wire payload, next residual)."""
    if residual is not None:
        delta = jax.tree.map(lambda d, r: d + r, delta, residual)
    payload, deq = quantize_tree(delta)
    new_residual = jax.tree.map(lambda d, x: d - x, delta, deq)
    return payload, new_residual


# --------------------------------------------------------------- top-k tier

TOPK_KEY = "__dkt_topk__"
DEFAULT_TOPK_FRAC = 0.01


def parse_compress_spec(spec):
    """``None | "int8" | "topk" | "topk:<frac>"`` -> (kind, frac|None).

    The fraction rides the spec string so the knob needs no extra kwarg
    through the trainer/worker constructors: ``compress="topk:0.05"``
    ships the largest 5% of each leaf's entries per commit."""
    if spec is None:
        return None, None
    if spec == "int8":
        return "int8", None
    if spec == "topk":
        return "topk", DEFAULT_TOPK_FRAC
    if isinstance(spec, str) and spec.startswith("topk:"):
        frac = float(spec.split(":", 1)[1])
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk fraction must be in (0, 1]; got {frac}")
        return "topk", frac
    raise ValueError(
        f"compress must be None, 'int8', 'topk' or 'topk:<frac>'; got {spec!r}"
    )


def _topk_leaf(a, frac):
    a = np.asarray(a, np.float32)
    if a.size and not np.isfinite(a).all():
        raise FloatingPointError(
            "non-finite delta leaf: refusing to sparsify a diverged update"
        )
    flat = a.ravel()
    n = flat.size
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.float32)
    k = max(1, int(np.ceil(frac * n)))
    if k >= n:
        idx = np.arange(n, dtype=np.int32)
    else:
        idx = np.argpartition(np.abs(flat), n - k)[n - k:].astype(np.int32)
    return idx, flat[idx]


def _topk_dense(idx, vals, shape):
    out = np.zeros(int(np.prod(shape)) if len(shape) else 1, np.float32)
    out[idx] = vals
    return out.reshape(tuple(int(d) for d in shape))


def topk_compress(tree, frac=DEFAULT_TOPK_FRAC):
    """-> (payload, dense reconstruction). Per leaf, ship only the k =
    ceil(frac * n) largest-|x| entries as (int32 index, float32 value)
    pairs — ~frac * 2 of the dense bytes (Deep-Gradient-Compression-style
    sparsification; the un-shipped mass is the caller's error-feedback
    residual). Wire format mirrors the int8 tier: plain arrays under one
    marker key, so the pickle-free DKT1 frame carries it unchanged."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    pairs = [_topk_leaf(a, frac) for a in flat]
    shapes = [np.asarray(np.shape(a), np.int64) for a in flat]
    unflat = jax.tree_util.tree_unflatten
    payload = {
        TOPK_KEY: {
            "i": unflat(treedef, [i for i, _ in pairs]),
            "v": unflat(treedef, [v for _, v in pairs]),
            "s": unflat(treedef, shapes),
        }
    }
    deq = unflat(
        treedef,
        [_topk_dense(i, v, s) for (i, v), s in zip(pairs, shapes)],
    )
    return payload, deq


def topk_decompress(payload):
    body = payload[TOPK_KEY]
    return jax.tree.map(
        lambda i, v, s: _topk_dense(i, v, s), body["i"], body["v"], body["s"]
    )


def is_topk(delta) -> bool:
    return isinstance(delta, dict) and set(delta.keys()) == {TOPK_KEY}


def topk_compress_with_feedback(delta, residual, frac=DEFAULT_TOPK_FRAC):
    """Worker-side entry: fold the previous residual in, sparsify, return
    (wire payload, next residual). Unshipped entries carry over entirely
    — momentum-free error feedback, the same conservation contract the
    int8 tier pins (sum of shipped + residual == sum of raw deltas)."""
    if residual is not None:
        delta = jax.tree.map(lambda d, r: d + r, delta, residual)
    payload, deq = topk_compress(delta, frac)
    new_residual = jax.tree.map(lambda d, x: d - x, delta, deq)
    return payload, new_residual
