"""Deterministic RNG key management.

The reference relied on Python/numpy global RNG (reference: distkeras/utils.py
-> shuffle and Keras init). JAX requires explicit threading of PRNG keys; this
sequence wrapper gives trainers/workers a deterministic, per-consumer stream.
"""

from __future__ import annotations

import jax


class RngSeq:
    """A splittable stream of jax PRNG keys: next() is deterministic in seed."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.PRNGKey(seed)

    def next(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_n(self, n: int):
        self._key, *subs = jax.random.split(self._key, n + 1)
        return list(subs)

    def fork(self, index: int) -> "RngSeq":
        """Deterministic per-worker fork (worker index -> independent stream)."""
        child = RngSeq.__new__(RngSeq)
        child._key = jax.random.fold_in(self._key, index)
        return child
