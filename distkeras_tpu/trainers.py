"""Trainer orchestration — the core public API.

Rebuild of the reference's trainer zoo (reference: distkeras/trainers.py ->
Trainer / SingleTrainer / EnsembleTrainer / AveragingTrainer /
DistributedTrainer / AsynchronousDistributedTrainer / DOWNPOUR / AEASGD /
EAMSGD / ADAG / DynSGD), same constructor vocabulary
(``worker_optimizer``, ``loss``, ``num_workers``, ``batch_size``,
``communication_window``, ``rho``, ``learning_rate``, ``num_epoch``) and the
same contract: ``trainer.train(dataset) -> trained Model``.

TPU-native mapping (SURVEY §7.1):

- Spark ``mapPartitionsWithIndex`` worker launch -> per-device workers over a
  ``jax.sharding.Mesh`` (threads for true asynchrony, or a seeded
  deterministic simulator for reproducible staleness in tests);
- the socket PS star topology -> in-process host-resident PS (optionally
  served over TCP for cross-host DCN workers);
- NEW first-class ``SynchronousDistributedTrainer``: per-step allreduce data
  parallelism — params replicated, batch sharded along ``Mesh(("data",))``,
  XLA inserts the gradient ``psum`` over ICI (this is the path the
  north-star benchmarks).
"""

from __future__ import annotations

import logging
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

from distkeras_tpu.ops.optimizers import effective_learning_rate, get_optimizer
from distkeras_tpu.parallel.mesh import (
    host_gather,
    local_devices,
    make_mesh,
    replicate,
    shard_opt_state_zero,
    zero_leaf_sharding,
)
from distkeras_tpu.parameter_servers import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    RemoteParameterServerClient,
    SocketParameterServer,
)
from distkeras_tpu.utils.checkpoint import Checkpointer
from distkeras_tpu.utils.history import TrainingHistory
from distkeras_tpu.utils.profiling import MetricsLogger, trace as profiler_trace
from distkeras_tpu.utils.serialization import serialize_model
from distkeras_tpu.utils.tree import host_copy, tree_mean
from distkeras_tpu.workers import (
    ADAGWorker,
    AEASGDWorker,
    AsyncWorker,
    DOWNPOURWorker,
    DynSGDWorker,
    EAMSGDWorker,
    SingleTrainerWorker,
    WorkerCore,
    _metrics_to_records,
    iter_windows,
    stack_window,
    state_leaf_name,
)


class Trainer:
    """Base trainer: model + optimizer/loss spec + history bookkeeping
    (reference: distkeras/trainers.py -> Trainer)."""

    supports_validation = True  # see validation_data handling in __init__

    def __init__(
        self,
        model,
        worker_optimizer="sgd",
        loss="categorical_crossentropy",
        metrics=("accuracy",),
        learning_rate=None,
        features_col="features",
        label_col="label",
        batch_size=32,
        num_epoch=1,
        seed=0,
        compute_dtype=None,
        remat=False,
        accum_steps=1,
        aux_loss_weight=0.01,
        profile_dir=None,
        metrics_path=None,
        validation_data=None,
    ):
        if model.params is None:
            raise ValueError("model must be built (call model.build(input_shape))")
        from distkeras_tpu.ops.quantization import count_quantized

        if count_quantized(model.params):
            raise ValueError(
                "model holds an int8-quantized serving tree "
                "(ops.quantization.quantize_model) — training cannot "
                "differentiate through round(); train the f32 master and "
                "quantize a serving copy instead"
            )
        # accum_steps=k: each optimizer step processes its batch as k
        # sequential microbatches of B/k, averaging the gradients — ~k x
        # less activation memory at (BN aside) full-batch numerics. B must
        # divide by k.
        self.accum_steps = int(accum_steps)
        if self.accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1; got {accum_steps}")
        if batch_size % self.accum_steps:
            raise ValueError(
                f"batch_size {batch_size} not divisible by accum_steps "
                f"{accum_steps}"
            )
        self.model = model
        # the lr the optimizer actually runs with — PS/elastic rules that
        # scale by lr (AEASGD, ADAG) must see the same value
        self.learning_rate = effective_learning_rate(worker_optimizer, learning_rate)
        self.worker_optimizer = worker_optimizer
        self.optimizer = get_optimizer(worker_optimizer, learning_rate)
        # structural spec for the WorkerCore program cache, derived from the
        # RAW constructor args: self.learning_rate is flattened to a
        # schedule's step-0 float above, so keying on it would collide two
        # different schedules (or a schedule with a constant) that share a
        # step-0 value — schedules and custom optax objects bypass the
        # cache instead. Subclasses that replace self.optimizer (EAMSGD)
        # must update this spec to match what they install.
        self._core_spec = (
            (worker_optimizer, repr(learning_rate))
            if isinstance(worker_optimizer, str)
            and isinstance(learning_rate, (int, float, type(None)))
            else None
        )
        self.loss = loss
        self.metrics = tuple(metrics)
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = int(seed)
        self.compute_dtype = compute_dtype
        self.remat = bool(remat)
        # weight on layer-emitted "aux_loss" state leaves (MoE load balance)
        self.aux_loss_weight = float(aux_loss_weight)
        self.history = TrainingHistory()
        # held-out set evaluated at each epoch end (Keras-style val_*
        # metrics in the history); None disables. Trainers without a
        # global epoch boundary (async: workers own their partitions for
        # all epochs) or without a single live params tree per epoch
        # (ensemble/averaging/pipeline) set supports_validation = False
        # and reject it loudly rather than silently recording nothing.
        # NOTE (ADVICE r2 #3): validation runs eval-mode BatchNorm, i.e.
        # running statistics. At the default bn_momentum=0.99 those stats
        # lag the batch stats by hundreds of steps, so early-epoch val_*
        # metrics on short runs sit well below train metrics even when the
        # model is learning; build BN models with bn_momentum~=0.9 when the
        # run is only a few hundred steps per epoch.
        if validation_data is not None and not self.supports_validation:
            raise TypeError(
                f"{type(self).__name__} does not support per-epoch "
                "validation_data — evaluate the returned model with "
                "ModelPredictor/AccuracyEvaluator instead"
            )
        self.validation_data = validation_data
        # observability (absent upstream — SURVEY §5.1/§5.5 required addition)
        self.profile_dir = profile_dir
        self.metrics_logger = MetricsLogger(metrics_path) if metrics_path else None

    def _make_core(self, optimizer=None) -> WorkerCore:
        # _core_spec fingerprints the optimizer the programs will close
        # over (set from raw ctor args in __init__; updated by subclasses
        # that swap self.optimizer); an explicit optimizer override is
        # never cached
        spec = self._core_spec if optimizer is None else None
        return WorkerCore.cached(
            self.model,
            optimizer or self.optimizer,
            self.loss,
            optimizer_spec=spec,
            metrics=self.metrics,
            compute_dtype=self.compute_dtype,
            remat=self.remat,
            accum_steps=self.accum_steps,
            aux_loss_weight=self.aux_loss_weight,
        )

    def _windowed_epochs(
        self,
        dataset,
        shuffle,
        cols,
        global_batch,
        window,
        start_epoch,
        carry,
        run_window,
        on_epoch_end=None,
        prepare=None,
        prefetch=0,
    ):
        """Shared epoch pump for the one-compiled-program trainers: group
        batches into windows of ``window`` steps, feed each through
        ``prepare`` (host staging: stack + device_put — run ``prefetch``
        windows ahead on a background thread so input work overlaps device
        compute) into ``run_window(carry, prepared) -> carry``; flush the
        remainder at epoch end, then fire ``on_epoch_end(epoch, carry)``
        (checkpoint hook). Window order is preserved, so trajectories are
        bit-identical with prefetch on or off."""
        from distkeras_tpu.data.prefetch import Prefetcher

        for epoch in range(start_epoch, self.num_epoch):
            ds = dataset.shuffle(self.seed + epoch) if shuffle else dataset
            with Prefetcher(
                iter_windows(ds, global_batch, cols, window),
                prepare,
                depth=prefetch,
            ) as staged:
                for prepared in staged:
                    carry = run_window(carry, prepared)
            if on_epoch_end is not None:
                on_epoch_end(epoch, carry)
        return carry

    def _finish(self, params, state=None):
        """Produce the result model (trained weights on a copy).

        In multi-controller runs a tree can come back sharded across
        processes (ZeRO moments; GSPMD sometimes leaves steady-state
        params data-sharded too) — ``np.asarray`` cannot fetch
        non-addressable shards, so such leaves are gathered first."""
        result = self.model.copy()
        result.params = jax.tree.map(np.asarray, host_gather(params))
        if state is not None:
            result.state = jax.tree.map(np.asarray, host_gather(state))
        return result

    # -- bookkeeping parity -------------------------------------------------

    def get_history(self, worker_id=None):
        return self.history.get_history(worker_id)

    def get_training_time(self):
        return self.history.get_training_time()

    def get_averaged_metrics(self):
        return self.history.averages()

    def serialize(self) -> bytes:
        return serialize_model(self.model)

    # -- checkpointing (absent upstream — SURVEY §5.4 required addition) ----

    def _init_checkpointing(self, checkpoint_dir, checkpoint_every, max_to_keep):
        self.checkpointer = (
            Checkpointer(checkpoint_dir, max_to_keep=max_to_keep)
            if checkpoint_dir
            else None
        )
        self.checkpoint_every = int(checkpoint_every)

    def _restore_latest(self):
        """(step, trees, meta) of the latest checkpoint, or None."""
        if self.checkpointer is None or self.checkpointer.latest_step() is None:
            return None
        return self.checkpointer.restore()

    def _should_checkpoint(self, done: int) -> bool:
        """THE epoch-snapshot policy: every `checkpoint_every` epochs
        (0 = final only) and always at the last epoch."""
        every = self.checkpoint_every
        return (every > 0 and done % every == 0) or done == self.num_epoch

    def _epoch_end(self, core, epoch, params, state, opt_state, rng):
        """THE per-epoch finalization shared by every windowed trainer:
        validate, then checkpoint (both no-ops when unconfigured)."""
        self._run_validation(core, params, state, epoch + 1)
        self._save_epoch_checkpoint(epoch + 1, params, state, opt_state, rng)

    def _run_validation(self, core, params, state, epoch):
        """Evaluate ``validation_data`` with the current params/state and
        record Keras-style ``val_*`` metrics for this epoch. Metrics are
        sample-weighted means over all validation batches (ragged tail
        included). Per-batch results stay on device until the end so
        eval dispatches pipeline instead of syncing every batch."""
        if self.validation_data is None:
            return None
        results = []
        for batch in self.validation_data.batches(
            self.batch_size,
            columns=[self.features_col, self.label_col],
            drop_remainder=False,
        ):
            x, y = batch[self.features_col], batch[self.label_col]
            results.append((core.eval_step(params, state, x, y), len(x)))
        if not results:
            return None
        totals, n = {}, 0
        for mets, b in results:
            for k, v in mets.items():
                totals[k] = totals.get(k, 0.0) + float(v) * b
            n += b
        avg = {f"val_{k}": v / n for k, v in totals.items()}
        self.history.record_validation(epoch, avg)
        if self.metrics_logger is not None:
            self.metrics_logger.log(event="validation", epoch=epoch, **avg)
        return avg

    def get_validation_history(self):
        return self.history.get_validation_history()

    def _reconcile_opt_state(self, candidate, core, params):
        """Restored optimizer moments, or None when the checkpoint was
        written in another layout (a pipeline trainer's '__blocks__'-stacked
        moments, a different optax chain) — THE cross-trainer resume policy,
        shared by every trainer that reads the common checkpoint format.
        Structure comes from ``eval_shape`` (no moment allocation)."""
        reference = jax.eval_shape(core.init_opt_state, params)
        if jax.tree.structure(candidate) == jax.tree.structure(reference):
            return candidate
        logger.warning(
            "checkpoint opt_state layout does not match this trainer; "
            "reinitializing optimizer state"
        )
        return None

    def _save_epoch_checkpoint(self, done, params, state, opt_state, rng):
        """Epoch-granular snapshots shared by SingleTrainer and the sync-DP
        trainer (policy: ``_should_checkpoint``)."""
        if self.checkpointer is None:
            return
        if self._should_checkpoint(done):
            # cross-process-sharded trees (ZeRO moments) gather to full
            # host arrays first — the snapshot format is a full tree
            self.checkpointer.save(
                done,
                {
                    "params": host_gather(params),
                    "state": host_gather(state),
                    "opt_state": host_gather(opt_state),
                    "rng": rng,
                },
                {"epoch": done},
            )

    def train(self, dataset, shuffle=False, **kwargs):
        """Public entry: optional device profile around the run (xprof trace
        into ``profile_dir``) + structured summary into ``metrics_path``."""
        if self.profile_dir:
            with profiler_trace(self.profile_dir):
                result = self._train(dataset, shuffle=shuffle, **kwargs)
        else:
            result = self._train(dataset, shuffle=shuffle, **kwargs)
        self._log_summary()
        return result

    def _log_summary(self):
        if self.metrics_logger is None:
            return
        avg = {f"avg_{k}": v for k, v in self.get_averaged_metrics().items()}
        self.metrics_logger.log(
            event="train_end",
            trainer=type(self).__name__,
            training_time=self.get_training_time(),
            num_updates=self.history.num_updates(),
            total_samples=self.history.total_samples(),
            samples_per_sec=self.history.samples_per_second(),
            **avg,
        )

    def _train(self, dataset, shuffle=False):
        raise NotImplementedError


class SingleTrainer(Trainer):
    """One worker, one device — the correctness anchor (reference:
    distkeras/trainers.py -> SingleTrainer; BASELINE config 1).

    ``prefetch`` (all trainers) defaults to 0: the four committed v5e
    A/Bs measured overlap speedups of 0.74/0.83/0.99/1.12 — a median
    LOSS — so background staging is opt-in until the interleaved-median
    protocol (tools/prefetch_ab.py) demonstrates a >= 1.0 win at these
    shapes (VERDICT r3 weak #4). Trajectories are bit-identical either
    way; only throughput is at stake."""

    def __init__(
        self,
        *args,
        window=8,
        device=None,
        prefetch=0,
        device_resident=False,
        checkpoint_dir=None,
        checkpoint_every=1,
        max_to_keep=3,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.window = int(window)
        self.device = device
        self.prefetch = int(prefetch)
        # dataset fits in HBM -> ship it once, stream only indices
        # (bit-identical to the streamed path; see WorkerCore.indexed_window)
        self.device_resident = bool(device_resident)
        self._init_checkpointing(checkpoint_dir, checkpoint_every, max_to_keep)

    def _train(self, dataset, shuffle=False, resume=False):
        self.history.record_training_start()
        core = self._make_core()
        worker = SingleTrainerWorker(
            core,
            self.features_col,
            self.label_col,
            seed=self.seed,
            device=self.device,
        )

        initial_full, start_epoch = None, 0
        if resume:
            restored = self._restore_latest()
            if restored is not None:
                _, trees, meta = restored
                opt_state = self._reconcile_opt_state(
                    trees["opt_state"], core, trees["params"]
                )
                if opt_state is None:  # foreign layout: moments restart
                    opt_state = core.init_opt_state(trees["params"])
                initial_full = (
                    trees["params"],
                    trees["state"],
                    opt_state,
                    trees["rng"],
                )
                start_epoch = int(meta["epoch"])

        on_epoch_end = None
        if self.checkpointer is not None or self.validation_data is not None:
            def on_epoch_end(epoch, params, state, opt_state, rng):
                self._epoch_end(core, epoch, params, state, opt_state, rng)

        params, state, records = worker.train(
            dataset,
            self.batch_size,
            num_epoch=self.num_epoch,
            window=self.window,
            shuffle_seed=self.seed if shuffle else None,
            initial_full=initial_full,
            start_epoch=start_epoch,
            on_epoch_end=on_epoch_end,
            prefetch=self.prefetch,
            device_resident=self.device_resident,
        )
        self.history.extend(0, records)
        for s, dt in worker.timings:
            self.history.record_window(0, s, dt)
        self.history.record_training_end()
        return self._finish(params, state)


class SynchronousDistributedTrainer(Trainer):
    """Per-step allreduce data parallelism over a device mesh.

    The batch (``batch_size`` per worker, ``batch_size * num_workers``
    global) is sharded along the "data" mesh axis; params/opt state are
    replicated; the global-mean loss makes XLA emit the gradient ``psum``
    over ICI inside the compiled step. This replaces the reference's
    pull/commit protocol entirely for the synchronous path [BASELINE
    north-star]. Windows of W steps are scanned inside one XLA program.

    ``shard_opt_state=True`` adds ZeRO-1: optimizer moments shard over
    the "data" axis (``parallel.mesh.zero_leaf_sharding``); each rank
    updates its slice and GSPMD places the rebuild collectives — it may
    all-gather p_new each step or keep steady-state params sharded too
    and gather at use (observed on the CPU mesh), whichever its cost
    model prefers. Either way: per-device optimizer memory drops
    ~num_workers-fold (2/3 of training-state bytes under adam) and the
    trajectory matches the replicated trainer (parity-pinned). No
    reference counterpart (SURVEY §3.3: no state sharding upstream).
    """

    def __init__(
        self,
        *args,
        num_workers=None,
        window=8,
        mesh=None,
        model_parallel=None,
        expert_parallel=None,
        shard_opt_state=False,
        prefetch=0,
        device_resident=False,
        checkpoint_dir=None,
        checkpoint_every=1,
        max_to_keep=3,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        # model_parallel=k: 2-D ("data", "model") mesh — batches shard over
        # "data" (gradient psum), Dense/conv output dims shard over "model"
        # (GSPMD inserts the activation collectives). SURVEY §3.3: TP is
        # absent upstream; this is the TPU stretch capability.
        # expert_parallel=k: 2-D ("data", "expert") mesh — MoE expert
        # stacks shard over "expert" (GSPMD inserts the token<->expert
        # all-to-all), everything else replicates; batches shard over
        # "data" as usual.
        self.model_parallel = int(model_parallel) if model_parallel else None
        self.expert_parallel = int(expert_parallel) if expert_parallel else None
        if self.model_parallel and self.expert_parallel:
            raise ValueError(
                "model_parallel and expert_parallel cannot combine on this "
                "trainer (their parameter sharding rules conflict); pick one"
            )
        # shard_opt_state=True: ZeRO-1 — optimizer moments shard over the
        # "data" axis; GSPMD places the param-rebuild collectives (see
        # class docstring), cutting per-device optimizer memory
        # ~num_workers-fold. Pure-DP only: TP/EP already shard their
        # moments along their own axes.
        self.shard_opt_state = bool(shard_opt_state)
        if self.shard_opt_state and (self.model_parallel or self.expert_parallel):
            raise ValueError(
                "shard_opt_state (ZeRO-1) applies to the pure data-parallel "
                "path; model_parallel/expert_parallel already shard their "
                "optimizer state along their own mesh axes"
            )
        sharded_axis = (
            ("model", self.model_parallel)
            if self.model_parallel
            else ("expert", self.expert_parallel)
            if self.expert_parallel
            else None
        )
        if mesh is not None:
            if sharded_axis and mesh.shape.get(sharded_axis[0]) != sharded_axis[1]:
                raise ValueError(
                    f"mesh {dict(mesh.shape)} does not have a "
                    f"'{sharded_axis[0]}' axis of size {sharded_axis[1]}"
                )
            self.mesh = mesh
        elif sharded_axis:
            axis_name, k = sharded_axis
            n_dev = len(local_devices())
            if num_workers:
                dp = int(num_workers)
            else:
                dp, rem = divmod(n_dev, k)
                if rem:
                    raise ValueError(
                        f"{axis_name}_parallel={k} does not divide the "
                        f"{n_dev} available devices"
                    )
            if dp < 1 or dp * k > n_dev:
                raise ValueError(
                    f"need {max(dp, 1) * k} devices for "
                    f"data={dp} x {axis_name}={k}, have {n_dev}"
                )
            devs = local_devices(dp * k)
            self.mesh = Mesh(
                np.array(devs).reshape(dp, k), ("data", axis_name)
            )
        else:
            self.mesh = make_mesh(num_workers)
        self.num_workers = int(self.mesh.shape.get("data", self.mesh.devices.size))
        self.window = int(window)
        self.prefetch = int(prefetch)
        # dataset replicated into every chip's HBM once; per-window the host
        # ships only the (W, B_global) index matrix, sharded over "data" so
        # each shard gathers its own rows (see WorkerCore.indexed_window)
        self.device_resident = bool(device_resident)
        self._init_checkpointing(checkpoint_dir, checkpoint_every, max_to_keep)

    def _place_params(self, params):
        """Replicated placement, or TP/EP shardings when enabled."""
        if self.model_parallel:
            from distkeras_tpu.parallel.tensor_parallel import shard_params

            return shard_params(params, self.mesh)
        if self.expert_parallel:
            from distkeras_tpu.parallel.expert_parallel import shard_moe_params

            return shard_moe_params(params, self.mesh)
        return replicate(params, self.mesh)

    def _place_opt_state(self, core, params, restored=None):
        """Optimizer-state placement matching the params placement. Under
        TP, init runs under jit so GSPMD propagates the params' shardings
        into momentum buffers; a restored state adopts those shardings.

        A restored state written in another layout (a pipeline trainer's
        '__blocks__'-stacked moments, or a different optax chain) is
        detected by tree structure and reinitialized instead of crashing
        the first window — params/state still restore, only the moments
        restart (mirrors PipelineParallelTrainer's guard for the reverse
        direction)."""
        if restored is not None:
            restored = self._reconcile_opt_state(restored, core, params)
        if self.model_parallel or self.expert_parallel:
            opt_state = jax.jit(core.init_opt_state)(params)
            if restored is not None:
                opt_state = jax.tree.map(
                    lambda r, placed: jax.device_put(r, placed.sharding),
                    restored,
                    opt_state,
                )
            return opt_state
        if self.shard_opt_state:
            if restored is not None:
                # host arrays shard straight to their slices (device_put
                # never materializes the full tree per device)
                return shard_opt_state_zero(restored, self.mesh)
            # fresh init runs under jit WITH the ZeRO out_shardings: an
            # eager init would materialize the full replicated state on
            # every device first — OOMing exactly the models ZeRO-1 is
            # meant to enable (r4 review finding)
            shapes = jax.eval_shape(core.init_opt_state, params)
            shardings = jax.tree.map(
                lambda s: zero_leaf_sharding(self.mesh, s), shapes
            )
            return jax.jit(
                core.init_opt_state, out_shardings=shardings
            )(params)
        if restored is not None:
            return replicate(restored, self.mesh)
        return replicate(core.init_opt_state(params), self.mesh)

    def _train(self, dataset, shuffle=False, resume=False):
        if not self.expert_parallel:
            return self._train_impl(dataset, shuffle, resume)
        # expert sharding is a process-local layer hook (like the ring
        # attention hook): attach for the run, detach so neither the
        # caller's model nor the returned copy closes over a live mesh
        from distkeras_tpu.parallel.expert_parallel import (
            attach_expert_mesh,
            detach_expert_mesh,
        )

        try:
            # inside the try: a mid-attach failure (e.g. a second MoE layer
            # whose num_experts doesn't divide the axis) must still detach
            # the layers already attached
            if attach_expert_mesh(self.model, self.mesh) == 0:
                raise ValueError(
                    "expert_parallel needs a model with MoE layers "
                    "(zoo.moe_transformer_classifier)"
                )
            return self._train_impl(dataset, shuffle, resume)
        finally:
            detach_expert_mesh(self.model)

    def _train_impl(self, dataset, shuffle=False, resume=False):
        self.history.record_training_start()
        core = self._make_core()
        global_batch = self.batch_size * self.num_workers

        start_epoch = 0
        restored = self._restore_latest() if resume else None
        if restored is not None:
            _, trees, meta = restored
            params = self._place_params(trees["params"])
            state = replicate(trees["state"], self.mesh)
            opt_state = self._place_opt_state(core, params, trees["opt_state"])
            rng = jax.device_put(trees["rng"])
            start_epoch = int(meta["epoch"])
        else:
            params = self._place_params(host_copy(self.model.params))
            state = replicate(host_copy(self.model.state), self.mesh)
            opt_state = self._place_opt_state(core, params)
            rng = jax.random.PRNGKey(self.seed)
        cols = [self.features_col, self.label_col]

        if self.device_resident:
            return self._train_resident(
                dataset,
                shuffle,
                core,
                global_batch,
                (params, state, opt_state, rng),
                start_epoch,
            )

        # windows stack to (W, B, ...): leave the window axis whole, shard
        # the batch axis. Constructed directly — NamedSharding.update(spec=)
        # was removed from JAX.
        win_sh = NamedSharding(self.mesh, P(None, "data"))

        def prepare(batches):
            # host staging (prefetch thread): batch shards along "data"
            xs, ys = stack_window(batches, self.features_col, self.label_col)
            xs = jax.device_put(xs, win_sh)
            ys = jax.device_put(ys, win_sh)
            return xs, ys

        def run_window(carry, prepared):
            params, state, opt_state, rng = carry
            xs, ys = prepared
            t0 = time.perf_counter()
            params, state, opt_state, rng, mets = core.window(
                params, state, opt_state, rng, xs, ys
            )
            self.history.extend(0, _metrics_to_records(mets))
            self.history.record_window(
                0, xs.shape[0] * xs.shape[1], time.perf_counter() - t0
            )
            return params, state, opt_state, rng

        params, state, opt_state, rng = self._windowed_epochs(
            dataset,
            shuffle,
            cols,
            global_batch,
            self.window,
            start_epoch,
            (params, state, opt_state, rng),
            run_window,
            lambda epoch, carry: self._epoch_end(core, epoch, *carry),
            prepare=prepare,
            prefetch=self.prefetch,
        )

        self.history.record_training_end()
        return self._finish(params, state)

    def _train_resident(
        self, dataset, shuffle, core, global_batch, carry, start_epoch
    ):
        """HBM-resident sync-DP epochs: the dataset is replicated into every
        chip's HBM once; per window the host ships only the (W, B_global)
        int32 index matrix, sharded along "data" — each shard gathers its
        own batch rows on-device, so the gather is collective-free and the
        step's gradient ``psum`` is unchanged. Batch assembly matches the
        streamed path permutation-for-permutation (bit-identical)."""
        from distkeras_tpu.parallel.mesh import replicated_sharding
        from distkeras_tpu.workers import epoch_index_windows, resident_arrays

        params, state, opt_state, rng = carry
        n = len(dataset)
        data_x, data_y = resident_arrays(dataset, self.features_col, self.label_col)
        if n // global_batch > 0:
            repl = replicated_sharding(self.mesh)
            data_x = jax.device_put(data_x, repl)
            data_y = jax.device_put(data_y, repl)
        idx_sh = NamedSharding(self.mesh, P(None, "data"))

        for epoch in range(start_epoch, self.num_epoch):
            for idx_host in epoch_index_windows(
                n, global_batch, self.window, self.seed if shuffle else None, epoch
            ):
                idx = jax.device_put(idx_host, idx_sh)
                t0 = time.perf_counter()
                params, state, opt_state, rng, mets = core.indexed_window(
                    params, state, opt_state, rng, data_x, data_y, idx
                )
                self.history.extend(0, _metrics_to_records(mets))
                self.history.record_window(0, idx.size, time.perf_counter() - t0)
            self._epoch_end(core, epoch, params, state, opt_state, rng)

        self.history.record_training_end()
        return self._finish(params, state)


class SequenceParallelTrainer(Trainer):
    """Sequence/context-parallel training — ring OR Ulysses attention.

    No reference counterpart (SURVEY §5.7: the reference's workloads have no
    sequence dimension); this trainer is the rebuild's long-context
    capability. The TOKEN axis of every batch is sharded across a
    ``Mesh(("seq",))`` — each device holds ``T / num_workers`` tokens —
    and every ``MultiHeadSelfAttention`` is pointed at the scheme chosen
    by ``sp_mode``:

    - ``"ring"`` (default, ``parallel.ring_attention``): K/V blocks
      rotate around the ring via ``lax.ppermute`` with an online softmax,
      so the full score matrix never materializes and per-device
      attention memory is O((T/N)^2). No head-count constraint.
    - ``"ulysses"`` (``parallel.ulysses``): one ``all_to_all`` re-shards
      tokens into head slices, each device attends over the FULL sequence
      for its heads (``sp_inner="dense"`` or ``"blockwise"``), a second
      ``all_to_all`` restores the token sharding. Two collectives per
      attention instead of N-1; num_heads must be divisible by the
      seq-axis size.

    Params are replicated; the loss reduces over batch AND token axes, so
    GSPMD inserts the gradient reductions across the "seq" axis
    automatically — the whole training step (including the collectives'
    transposes in the backward pass) is ONE compiled XLA program.
    Windows of W steps scan inside that program like every other trainer.

    The returned model computes dense attention (the hooks close over a
    live mesh and are process-local); call
    ``parallel.ring_attention.attach_ring_attention`` /
    ``parallel.ulysses.attach_ulysses_attention`` again to serve
    long-context inference sharded.
    """

    def __init__(
        self,
        *args,
        num_workers=None,
        window=8,
        mesh=None,
        data_parallel=1,
        prefetch=0,
        checkpoint_dir=None,
        checkpoint_every=1,
        max_to_keep=3,
        sp_mode="ring",
        sp_inner="dense",
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        # sp_mode: how attention crosses the sequence shards — "ring"
        # (K/V ppermute rotation, no head constraint) or "ulysses"
        # (all-to-all head sharding, 2 collectives instead of N-1;
        # num_heads must be divisible by the seq-axis size). sp_inner
        # picks ulysses' per-device attention over the full sequence:
        # "dense" or "blockwise" (online-softmax scan — (seq, block) score
        # memory, the long-context setting). See parallel/ulysses.py for
        # the trade-offs.
        if sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_mode must be 'ring' or 'ulysses'; got {sp_mode!r}"
            )
        if sp_inner not in ("dense", "blockwise"):
            raise ValueError(
                f"sp_inner must be 'dense' or 'blockwise'; got {sp_inner!r}"
            )
        self.sp_mode = sp_mode
        self.sp_inner = sp_inner
        if mesh is not None:
            if "seq" not in mesh.axis_names:
                raise ValueError(f"mesh {dict(mesh.shape)} has no 'seq' axis")
            if int(data_parallel) > 1 and "data" not in mesh.axis_names:
                raise ValueError(
                    f"data_parallel={data_parallel} conflicts with the "
                    f"supplied mesh {dict(mesh.shape)} — give the mesh a "
                    "'data' axis or drop data_parallel"
                )
            self.mesh = mesh
        else:
            devs = local_devices(num_workers)
            dp = int(data_parallel)
            if dp > 1:
                # 2-D batch x token sharding (VERDICT r2 weak #5): on a pod
                # you shard batch over "data" AND tokens over "seq"; the
                # loss reduces over both, so GSPMD psums gradients across
                # the full mesh while the attention ring stays within each
                # data slice
                if len(devs) % dp:
                    raise ValueError(
                        f"{len(devs)} devices not divisible by "
                        f"data_parallel={dp}"
                    )
                self.mesh = Mesh(
                    np.array(devs).reshape(dp, len(devs) // dp),
                    ("data", "seq"),
                )
            else:
                self.mesh = make_mesh(axis_names=("seq",), devices=devs)
        self.seq_size = int(self.mesh.shape["seq"])
        self.data_size = int(dict(self.mesh.shape).get("data", 1))
        self.num_workers = self.seq_size * self.data_size
        self.window = int(window)
        self.prefetch = int(prefetch)
        self._init_checkpointing(checkpoint_dir, checkpoint_every, max_to_keep)

    def _train(self, dataset, shuffle=False, resume=False):
        from distkeras_tpu.parallel.ring_attention import (
            attach_ring_attention,
            detach_ring_attention,
        )

        batch_axis = "data" if self.data_size > 1 else None
        if self.sp_mode == "ulysses":
            from distkeras_tpu.parallel.ulysses import attach_ulysses_attention

            attached = attach_ulysses_attention(
                self.model, self.mesh, "seq", batch_axis=batch_axis,
                inner=self.sp_inner,
            )
        else:
            attached = attach_ring_attention(
                self.model, self.mesh, "seq", batch_axis=batch_axis
            )
        if attached == 0:
            raise ValueError(
                "model has no MultiHeadSelfAttention layers — sequence "
                "parallelism needs an attention model (zoo.transformer_classifier)"
            )
        self.history.record_training_start()
        core = self._make_core()

        start_epoch = 0
        restored = self._restore_latest() if resume else None
        if restored is not None:
            _, trees, meta = restored
            params = replicate(trees["params"], self.mesh)
            state = replicate(trees["state"], self.mesh)
            moments = self._reconcile_opt_state(
                trees["opt_state"], core, trees["params"]
            )
            opt_state = replicate(
                moments if moments is not None else core.init_opt_state(params),
                self.mesh,
            )
            rng = jax.device_put(trees["rng"])
            start_epoch = int(meta["epoch"])
        else:
            params = replicate(host_copy(self.model.params), self.mesh)
            state = replicate(host_copy(self.model.state), self.mesh)
            opt_state = replicate(core.init_opt_state(params), self.mesh)
            rng = jax.random.PRNGKey(self.seed)

        # (W, B, T) token ids: batch shards along "data" (when 2-D), token
        # axis along "seq"; labels follow the batch sharding
        seq_sh = NamedSharding(self.mesh, P(None, batch_axis, "seq"))
        lbl_sh = NamedSharding(self.mesh, P(None, batch_axis))
        cols = [self.features_col, self.label_col]

        def prepare(batches):
            # host staging (prefetch thread)
            xs, ys = stack_window(batches, self.features_col, self.label_col)
            if xs.shape[2] % self.seq_size:
                raise ValueError(
                    f"sequence length {xs.shape[2]} is not divisible by the "
                    f"'seq' mesh size {self.seq_size} — pad the sequences "
                    "or change the mesh"
                )
            if xs.shape[1] % self.data_size:
                raise ValueError(
                    f"batch size {xs.shape[1]} is not divisible by the "
                    f"'data' mesh size {self.data_size}"
                )
            xs = jax.device_put(xs, seq_sh)
            ys = jax.device_put(ys, lbl_sh)
            return xs, ys

        def run_window(carry, prepared):
            params, state, opt_state, rng = carry
            xs, ys = prepared
            t0 = time.perf_counter()
            params, state, opt_state, rng, mets = core.window(
                params, state, opt_state, rng, xs, ys
            )
            self.history.extend(0, _metrics_to_records(mets))
            self.history.record_window(
                0, xs.shape[0] * xs.shape[1], time.perf_counter() - t0
            )
            return params, state, opt_state, rng

        try:
            params, state, opt_state, rng = self._windowed_epochs(
                dataset,
                shuffle,
                cols,
                self.batch_size,
                self.window,
                start_epoch,
                (params, state, opt_state, rng),
                run_window,
                lambda epoch, carry: self._epoch_end(core, epoch, *carry),
                prepare=prepare,
                prefetch=self.prefetch,
            )
        finally:
            # the hook closes over a live process-local Mesh, and
            # Model.copy() shares layer objects — detaching here keeps BOTH
            # the caller's model and the returned copy on dense attention,
            # as the class docstring promises
            detach_ring_attention(self.model)

        self.history.record_training_end()
        return self._finish(params, state)


class _PipelineModelShim:
    """Model-shaped adapter whose apply() runs the block tower through
    ``pipeline_apply`` — lets WorkerCore compile a pipelined train step
    without knowing about pipelining."""

    def __init__(
        self, model, pre_idx, block_idx, post_idx, mesh, num_micro,
        batch_axis=None,
    ):
        from distkeras_tpu.parallel.pipeline_parallel import pipeline_apply

        self._pipeline_apply = pipeline_apply
        self.layers = model.layers
        self.pre_idx = list(pre_idx)
        self.block_idx = list(block_idx)
        self.post_idx = list(post_idx)
        self.block_layer = model.layers[block_idx[0]]
        # blocks are stateless + rng-free (enforced by _find_block_run):
        # the scanned schedule threads neither state nor per-block rngs
        self.block_state = model.state[str(block_idx[0])]
        self.mesh = mesh
        self.num_micro = num_micro
        self.batch_axis = batch_axis

    def apply(self, params, state, x, train=False, rng=None):
        rngs = (
            jax.random.split(rng, len(self.layers))
            if rng is not None
            else [None] * len(self.layers)
        )
        new_state = dict(state)
        h = x
        for i in self.pre_idx:
            h, new_state[str(i)] = self.layers[i].apply(
                params[str(i)], state[str(i)], h, train, rngs[i]
            )

        def block_apply(p, hh):
            out, _ = self.block_layer.apply(p, self.block_state, hh, train, None)
            return out

        h = self._pipeline_apply(
            params["__blocks__"], h, block_apply, self.mesh,
            num_micro=self.num_micro, batch_axis=self.batch_axis,
        )
        for i in self.post_idx:
            h, new_state[str(i)] = self.layers[i].apply(
                params[str(i)], state[str(i)], h, train, rngs[i]
            )
        return h, new_state


class PipelineParallelTrainer(Trainer):
    """Pipeline-parallel training: GPipe microbatching over a ``("pipe",)``
    mesh.

    No reference counterpart (SURVEY §3.3: no model sharding upstream).
    The model must contain a contiguous run of identically-configured,
    stateless, rng-free blocks (``zoo.transformer_classifier``'s
    TransformerBlock tower is the canonical case) whose length divides the
    mesh size. The trainer re-layouts those blocks' params onto a stacked
    leading stage axis sharded over ``"pipe"`` — each device holds
    ``depth/S`` blocks, so block memory scales 1/S — and the compiled
    window runs the GPipe schedule (activations hop stages via ppermute;
    the backward pass retraces the ring). Pre/post layers and the batch
    are replicated. The returned model is a NORMAL model with the blocks
    unstacked: pipelining is an execution-layout concern, invisible in the
    result (and in checkpoints, which store the unstacked layout).
    """

    supports_validation = False

    def __init__(
        self,
        *args,
        num_workers=None,
        window=8,
        mesh=None,
        num_micro=None,
        data_parallel=1,
        prefetch=0,
        checkpoint_dir=None,
        checkpoint_every=1,
        max_to_keep=3,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        if mesh is not None:
            if "pipe" not in mesh.axis_names:
                raise ValueError(f"mesh {dict(mesh.shape)} has no 'pipe' axis")
            if int(data_parallel) > 1 and "data" not in mesh.axis_names:
                raise ValueError(
                    f"data_parallel={data_parallel} conflicts with the "
                    f"supplied mesh {dict(mesh.shape)} — give the mesh a "
                    "'data' axis or drop data_parallel"
                )
            self.mesh = mesh
        else:
            devs = local_devices(num_workers)
            dp = int(data_parallel)
            if dp > 1:
                # 2-D pipeline x data sharding (VERDICT r2 weak #5): stages
                # shard the block tower over "pipe" while each data slice
                # pipelines its own batch shard; gradients psum over "data"
                # via GSPMD (params replicated across it)
                if len(devs) % dp:
                    raise ValueError(
                        f"{len(devs)} devices not divisible by "
                        f"data_parallel={dp}"
                    )
                self.mesh = Mesh(
                    np.array(devs).reshape(len(devs) // dp, dp),
                    ("pipe", "data"),
                )
            else:
                self.mesh = make_mesh(axis_names=("pipe",), devices=devs)
        self.pipe_size = int(self.mesh.shape["pipe"])
        self.data_size = int(dict(self.mesh.shape).get("data", 1))
        self.num_workers = self.pipe_size  # stage count drives block layout
        self.num_micro = int(num_micro) if num_micro else self.pipe_size
        self.window = int(window)
        self.prefetch = int(prefetch)
        self._init_checkpointing(checkpoint_dir, checkpoint_every, max_to_keep)

    # -- block-run discovery -------------------------------------------------

    def _find_block_run(self):
        """Longest contiguous run of identically-configured layers whose
        length divides the pipe mesh size; must also be stateless."""
        layers = self.model.layers
        runs = []
        start = 0
        for i in range(1, len(layers) + 1):
            if i == len(layers) or (
                layers[i].get_config() != layers[start].get_config()
            ):
                runs.append((start, i))
                start = i
        runs.sort(key=lambda r: r[1] - r[0], reverse=True)
        from distkeras_tpu.models.sequential import walk_layers

        for s, e in runs:
            depth = e - s
            if depth >= self.num_workers and depth % self.num_workers == 0:
                stateless = all(
                    not jax.tree.leaves(self.model.state[str(i)])
                    for i in range(s, e)
                )
                # the scanned schedule threads neither state nor per-block
                # rngs: rng-consuming blocks (Dropout towers) are excluded
                rng_free = all(
                    not sub.uses_train_rng
                    for sub in walk_layers(layers[s:e])
                )
                if stateless and rng_free:
                    return list(range(s, e))
        raise ValueError(
            "no contiguous run of >= num_workers identically-configured "
            "stateless blocks divisible by the pipe mesh size "
            f"({self.num_workers}) — pipeline parallelism needs a "
            "homogeneous block tower (zoo.transformer_classifier)"
        )

    def _stack(self, params_by_layer, block_idx):
        from distkeras_tpu.parallel.pipeline_parallel import stack_block_params

        return stack_block_params([params_by_layer[str(i)] for i in block_idx])

    def _unstack_into(self, pipe_params, block_idx):
        """Pipelined layout -> normal per-layer params dict (host arrays)."""
        from distkeras_tpu.parallel.pipeline_parallel import unstack_block_params

        out = {}
        blocks = unstack_block_params(pipe_params["__blocks__"])
        for i in range(len(self.model.layers)):
            if i in block_idx:
                out[str(i)] = jax.tree.map(
                    np.asarray, blocks[block_idx.index(i)]
                )
            else:
                out[str(i)] = jax.tree.map(np.asarray, pipe_params[str(i)])
        return out

    # -- train ---------------------------------------------------------------

    def _train(self, dataset, shuffle=False, resume=False):
        self.history.record_training_start()
        block_idx = self._find_block_run()
        other_idx = [
            i for i in range(len(self.model.layers)) if i not in block_idx
        ]
        pre_idx = [i for i in other_idx if i < block_idx[0]]
        post_idx = [i for i in other_idx if i > block_idx[-1]]

        batch_axis = "data" if self.data_size > 1 else None
        shim = _PipelineModelShim(
            self.model, pre_idx, block_idx, post_idx, self.mesh,
            self.num_micro, batch_axis=batch_axis,
        )

        start_epoch = 0
        restored = self._restore_latest() if resume else None
        source_params = (
            restored[1]["params"] if restored is not None else host_copy(self.model.params)
        )
        source_state = (
            restored[1]["state"] if restored is not None else host_copy(self.model.state)
        )
        if restored is not None:
            start_epoch = int(restored[2]["epoch"])

        from distkeras_tpu.parallel.pipeline_parallel import shard_stacked_params

        repl = NamedSharding(self.mesh, P())
        params = {
            "__blocks__": shard_stacked_params(
                self._stack(source_params, block_idx), self.mesh
            ),
            **{
                str(i): jax.device_put(source_params[str(i)], repl)
                for i in other_idx
            },
        }
        state = {
            str(i): jax.device_put(source_state[str(i)], repl)
            for i in range(len(self.model.layers))
        }

        core = WorkerCore(
            shim,
            self.optimizer,
            self.loss,
            metrics=self.metrics,
            compute_dtype=self.compute_dtype,
            remat=self.remat,
            # composes: each accumulation microbatch runs the full GPipe
            # schedule over its B/accum rows (the schedule's own num_micro
            # subdivides those further)
            accum_steps=self.accum_steps,
            aux_loss_weight=self.aux_loss_weight,
        )
        # jitted init lets GSPMD propagate the blocks' pipe sharding into
        # the optimizer moments
        opt_state = jax.jit(core.init_opt_state)(params)
        if restored is not None and "opt_state" in restored[1]:
            candidate = self._reconcile_opt_state(
                restored[1]["opt_state"], core, params
            )
            if candidate is not None:
                # same pipeline geometry: adopt the restored moments. The
                # host leaves stay UNCOMMITTED (no device_put) — the
                # compiled window lays them out to match the params'
                # shardings; a fixed placement would conflict with the
                # mesh-committed params. A foreign layout (per-layer
                # checkpoint from another trainer) keeps the fresh init.
                opt_state = candidate
        rng = (
            jax.device_put(restored[1]["rng"])
            if restored is not None
            else jax.random.PRNGKey(self.seed)
        )

        cols = [self.features_col, self.label_col]
        # batch shards over "data" when 2-D; (W, B, ...) — B is axis 1
        in_sh = (
            NamedSharding(self.mesh, P(None, "data"))
            if batch_axis is not None
            else repl
        )

        def prepare(batches):
            xs, ys = stack_window(batches, self.features_col, self.label_col)
            if xs.shape[1] % (self.data_size * self.num_micro):
                raise ValueError(
                    f"batch size {xs.shape[1]} must divide by num_micro*"
                    f"data_parallel = {self.num_micro}*{self.data_size}"
                )
            return jax.device_put(xs, in_sh), jax.device_put(ys, in_sh)

        def run_window(carry, prepared):
            params, state, opt_state, rng = carry
            xs, ys = prepared
            t0 = time.perf_counter()
            params, state, opt_state, rng, mets = core.window(
                params, state, opt_state, rng, xs, ys
            )
            self.history.extend(0, _metrics_to_records(mets))
            self.history.record_window(
                0, xs.shape[0] * xs.shape[1], time.perf_counter() - t0
            )
            return params, state, opt_state, rng

        def on_epoch_end(epoch, carry):
            if self.checkpointer is None:
                return
            done = epoch + 1
            if not self._should_checkpoint(done):
                return
            params, state, opt_state, rng = carry
            # checkpoints store the NORMAL layout for interop; opt_state
            # stays in pipeline layout (it only matters to resumed pipeline
            # runs with the same geometry)
            self.checkpointer.save(
                done,
                {
                    "params": self._unstack_into(params, block_idx),
                    "state": jax.tree.map(np.asarray, state),
                    "opt_state": jax.tree.map(np.asarray, opt_state),
                    "rng": np.asarray(rng),
                },
                {"epoch": done},
            )

        params, state, opt_state, rng = self._windowed_epochs(
            dataset,
            shuffle,
            cols,
            self.batch_size,
            self.window,
            start_epoch,
            (params, state, opt_state, rng),
            run_window,
            on_epoch_end,
            prepare=prepare,
            prefetch=self.prefetch,
        )

        self.history.record_training_end()
        return self._finish(self._unstack_into(params, block_idx), state)


def _member_mesh(m: int) -> Mesh:
    """1-D ("ensemble",) mesh over as many devices as divide the member
    count evenly (vmapped member-axis sharding needs equal shards)."""
    n_dev = len(local_devices())
    n = min(m, n_dev)
    while m % n:
        n -= 1
    if n < min(m, n_dev):
        logger.warning(
            "vmapped member training: %d members only shard over %d of %d "
            "devices (the member axis must divide evenly); pick a member "
            "count that is a multiple of the device count for full "
            "utilization",
            m, n, n_dev,
        )
    return Mesh(np.array(local_devices(n)), ("ensemble",))


def _joint_member_windows(parts, batch_size, cols, window):
    """Joint window stream for vmapped member training: per step, one
    window from EVERY member's partition, truncated to the shortest
    (members must step with identical shapes; tails differ by at most one
    batch across near-equal partitions)."""
    streams = [iter_windows(p, batch_size, cols, window) for p in parts]
    while True:
        wnds = [next(s, None) for s in streams]
        if any(w is None for w in wnds):
            return
        depth = min(len(w) for w in wnds)
        yield [w[:depth] for w in wnds]


def _member_prepare(cols, member_sh):
    """Host-staging closure for the prefetch thread: stack the member axis
    and ship with the member sharding while the device computes."""

    def prepare(wnds):
        staged = [stack_window(w, *cols) for w in wnds]
        xs = jax.device_put(np.stack([a for a, _ in staged]), member_sh)
        ys = jax.device_put(np.stack([b for _, b in staged]), member_sh)
        return xs, ys

    return prepare


def _record_member_step(history, m, mets, xs, dt):
    """Per-joint-step bookkeeping shared by the vmapped member trainers:
    split the (member, window) metric arrays into per-member history
    records and attribute the step's wall time across members."""
    host_mets = {k: np.asarray(v) for k, v in mets.items()}
    for i in range(m):
        history.extend(
            i, _metrics_to_records({k: v[i] for k, v in host_mets.items()})
        )
        history.record_window(i, xs.shape[1] * xs.shape[2], dt / m)


class EnsembleTrainer(Trainer):
    """Train ``num_models`` independent models on disjoint partitions; return
    the list (reference: distkeras/trainers.py -> EnsembleTrainer).

    ``vmapped=True`` is the TPU-shaped execution (SURVEY §3.3: ensemble
    parallelism "trivial under pmap over the model axis"): every member's
    params/opt-state stack on a leading member axis sharded over an
    ``("ensemble",)`` mesh, and ONE jitted ``vmap`` of the window program
    trains all members per step — one compile per window length, no Python
    threads, members ride devices via sharding. Members see the same
    per-partition window streams as the threaded path; each joint step
    truncates to the SHORTEST member's window (members must step with
    identical shapes), so batches past the shortest tail are dropped —
    size partitions to tile evenly for exact thread-mode parity."""

    supports_validation = False

    def __init__(
        self, *args, num_models=2, window=8, vmapped=False, prefetch=0,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.num_models = int(num_models)
        self.window = int(window)
        self.vmapped = bool(vmapped)
        self.prefetch = int(prefetch)

    def _train(self, dataset, shuffle=False, resume=False):
        if resume:
            raise ValueError("EnsembleTrainer does not support resume")
        if self.vmapped:
            return self._train_vmapped(dataset, shuffle)
        self.history.record_training_start()
        parts = (dataset.shuffle(self.seed) if shuffle else dataset).partition(
            self.num_models
        )
        devices = local_devices()
        results = [None] * self.num_models

        core = self._make_core()

        def run(i):
            # independent init per ensemble member, shared compiled core
            model_i = self.model.copy()
            model_i.build(self.model.input_shape, seed=self.seed + i)
            worker = SingleTrainerWorker(
                core,
                self.features_col,
                self.label_col,
                seed=self.seed + i,
                device=devices[i % len(devices)],
            )
            params, state, records = worker.train(
                parts[i],
                self.batch_size,
                num_epoch=self.num_epoch,
                window=self.window,
                initial=(model_i.params, model_i.state),
            )
            self.history.extend(i, records)
            for s, dt in worker.timings:
                self.history.record_window(i, s, dt)
            model_i.params = jax.tree.map(np.asarray, params)
            model_i.state = jax.tree.map(np.asarray, state)
            results[i] = model_i

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(self.num_models)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.history.record_training_end()
        return results

    def _train_vmapped(self, dataset, shuffle=False):
        self.history.record_training_start()
        m = self.num_models
        core = self._make_core()
        parts = (dataset.shuffle(self.seed) if shuffle else dataset).partition(m)
        member_sh = NamedSharding(_member_mesh(m), P("ensemble"))

        # independent init per member (same contract as the threaded path),
        # stacked on the leading member axis
        members = []
        for i in range(m):
            model_i = self.model.copy()
            model_i.build(self.model.input_shape, seed=self.seed + i)
            members.append(model_i)
        params = jax.device_put(
            jax.tree.map(lambda *xs: np.stack(xs), *[mm.params for mm in members]),
            member_sh,
        )
        state = jax.device_put(
            jax.tree.map(lambda *xs: np.stack(xs), *[mm.state for mm in members]),
            member_sh,
        )
        opt_state = jax.device_put(
            jax.jit(jax.vmap(core.init_opt_state))(params), member_sh
        )
        rngs = jax.device_put(
            np.stack(
                [np.asarray(jax.random.PRNGKey(self.seed + i)) for i in range(m)]
            ),
            member_sh,
        )

        vm_window = jax.jit(jax.vmap(core.window_fn), donate_argnums=(0, 1, 2))
        cols = [self.features_col, self.label_col]

        from distkeras_tpu.data.prefetch import Prefetcher

        for _epoch in range(self.num_epoch):
            with Prefetcher(
                _joint_member_windows(parts, self.batch_size, cols, self.window),
                _member_prepare(cols, member_sh),
                depth=self.prefetch,
            ) as staged_windows:
                for xs, ys in staged_windows:
                    t0 = time.perf_counter()
                    params, state, opt_state, rngs, mets = vm_window(
                        params, state, opt_state, rngs, xs, ys
                    )
                    _record_member_step(
                        self.history, m, mets, xs, time.perf_counter() - t0
                    )

        params_host = jax.tree.map(np.asarray, params)
        state_host = jax.tree.map(np.asarray, state)
        for i, model_i in enumerate(members):
            model_i.params = jax.tree.map(lambda a: a[i], params_host)
            model_i.state = jax.tree.map(lambda a: a[i], state_host)
        self.history.record_training_end()
        return members


class AveragingTrainer(Trainer):
    """Per epoch: train a replica per partition from the current center, then
    average the replicas' weights (reference: distkeras/trainers.py ->
    AveragingTrainer).

    ``vmapped=True`` runs all replicas in ONE jitted ``vmap`` of the window
    program per joint step (replica axis sharded over an ``("ensemble",)``
    mesh) and takes the epoch-end average on device — same shape contract
    as ``EnsembleTrainer(vmapped=True)``: joint steps truncate to the
    shortest replica window, so size partitions to tile evenly for exact
    thread-mode parity."""

    supports_validation = False

    def __init__(
        self, *args, num_workers=2, window=8, vmapped=False, prefetch=0,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.num_workers = int(num_workers)
        self.window = int(window)
        self.vmapped = bool(vmapped)
        self.prefetch = int(prefetch)

    def _train(self, dataset, shuffle=False, resume=False):
        if resume:
            raise ValueError("AveragingTrainer does not support resume")
        if self.vmapped:
            return self._train_vmapped(dataset, shuffle)
        self.history.record_training_start()
        core = self._make_core()
        parts = (dataset.shuffle(self.seed) if shuffle else dataset).partition(
            self.num_workers
        )
        devices = local_devices()
        center = host_copy(self.model.params)
        state = host_copy(self.model.state)

        for epoch in range(self.num_epoch):
            results = [None] * self.num_workers

            def run(i, center=center, state=state):
                dev = devices[i % len(devices)]
                params_i = jax.device_put(center, dev)
                state_i = jax.device_put(state, dev)
                opt_i = jax.device_put(core.init_opt_state(params_i), dev)
                rng = jax.random.fold_in(
                    jax.random.PRNGKey(self.seed + epoch), i
                )
                records = []
                pend = []
                for batch in parts[i].batches(
                    self.batch_size, columns=[self.features_col, self.label_col]
                ):
                    pend.append(batch)
                    if len(pend) == self.window:
                        t0 = time.perf_counter()
                        xs, ys = stack_window(
                            pend, self.features_col, self.label_col
                        )
                        xs, ys = jax.device_put((xs, ys), dev)
                        params_i, state_i, opt_i, rng, mets = core.window(
                            params_i, state_i, opt_i, rng, xs, ys
                        )
                        records.extend(_metrics_to_records(mets))
                        self.history.record_window(
                            i, xs.shape[0] * xs.shape[1], time.perf_counter() - t0
                        )
                        pend = []
                if pend:
                    t0 = time.perf_counter()
                    xs, ys = stack_window(pend, self.features_col, self.label_col)
                    xs, ys = jax.device_put((xs, ys), dev)
                    params_i, state_i, opt_i, rng, mets = core.window(
                        params_i, state_i, opt_i, rng, xs, ys
                    )
                    records.extend(_metrics_to_records(mets))
                    self.history.record_window(
                        i, xs.shape[0] * xs.shape[1], time.perf_counter() - t0
                    )
                self.history.extend(i, records)
                results[i] = (
                    jax.tree.map(np.asarray, params_i),
                    jax.tree.map(np.asarray, state_i),
                )

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(self.num_workers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # host_copy: tree_mean yields default-device JAX arrays, which the
            # next epoch's windows would donate while other workers still
            # reference them
            center = host_copy(tree_mean([r[0] for r in results]))
            state = results[0][1]

        self.history.record_training_end()
        return self._finish(center, state)

    def _train_vmapped(self, dataset, shuffle=False):
        self.history.record_training_start()
        m = self.num_workers
        core = self._make_core()
        parts = (dataset.shuffle(self.seed) if shuffle else dataset).partition(m)
        member_sh = NamedSharding(_member_mesh(m), P("ensemble"))

        vm_window = jax.jit(jax.vmap(core.window_fn), donate_argnums=(0, 1, 2))
        vm_init = jax.jit(jax.vmap(core.init_opt_state))
        cols = [self.features_col, self.label_col]

        from distkeras_tpu.data.prefetch import Prefetcher

        center = host_copy(self.model.params)
        center_state = host_copy(self.model.state)

        for epoch in range(self.num_epoch):
            # every replica restarts the epoch from the shared center with
            # a fresh optimizer, exactly like the threaded path
            params = jax.device_put(
                jax.tree.map(lambda a: np.stack([a] * m), center), member_sh
            )
            state = jax.device_put(
                jax.tree.map(lambda a: np.stack([a] * m), center_state),
                member_sh,
            )
            opt_state = jax.device_put(vm_init(params), member_sh)
            rngs = jax.device_put(
                np.stack(
                    [
                        np.asarray(
                            jax.random.fold_in(
                                jax.random.PRNGKey(self.seed + epoch), i
                            )
                        )
                        for i in range(m)
                    ]
                ),
                member_sh,
            )
            with Prefetcher(
                _joint_member_windows(parts, self.batch_size, cols, self.window),
                _member_prepare(cols, member_sh),
                depth=self.prefetch,
            ) as staged_windows:
                for xs, ys in staged_windows:
                    t0 = time.perf_counter()
                    params, state, opt_state, rngs, mets = vm_window(
                        params, state, opt_state, rngs, xs, ys
                    )
                    _record_member_step(
                        self.history, m, mets, xs, time.perf_counter() - t0
                    )
            # epoch-end averaging: reduce on DEVICE, transfer only the
            # 1/m-sized result; state follows the threaded path's
            # convention (replica 0's)
            center = jax.tree.map(
                lambda a: np.asarray(jnp.mean(a, axis=0)), params
            )
            center_state = jax.tree.map(lambda a: np.asarray(a[0]), state)

        self.history.record_training_end()
        return self._finish(center, center_state)


def _maybe_len(dataset):
    try:
        return len(dataset)
    except TypeError:
        return None


class DistributedTrainer(Trainer):
    """Template for PS-based distributed training (reference:
    distkeras/trainers.py -> DistributedTrainer): partition data, start the
    PS, launch workers, collect, read the center back.

    ``mode``: "threads" (true async, one thread per worker, workers mapped
    round-robin onto devices) or "simulated" (seeded deterministic
    interleaving of pull/commit across workers — reproducible staleness for
    tests; SURVEY §7.3).
    """

    supports_validation = False

    worker_cls = None
    ps_cls = DeltaParameterServer

    def __init__(
        self,
        *args,
        num_workers=2,
        communication_window=5,
        mode="threads",
        serve_socket=False,
        remote_ps=False,
        standby=False,
        checkpoint_dir=None,
        checkpoint_every=0,
        max_to_keep=3,
        worker_snapshot_stride=1,
        worker_retries=1,
        heartbeat_timeout=None,
        elastic=False,
        device_resident=False,
        compress=None,
        pull_compress=None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.num_workers = int(num_workers)
        self.communication_window = int(communication_window)
        # compress="int8": commit deltas ride the wire quantized with
        # error feedback (utils/compression) — ~4x fewer commit bytes on
        # the DCN path; the PS dequantizes transparently.
        # compress="topk" / "topk:<frac>": Deep-Gradient-Compression-style
        # sparsification — ship only the k = ceil(frac*n) largest-|x|
        # entries per leaf (~frac*2 of the dense bytes; default frac 0.01
        # -> ~50x fewer commit bytes), unshipped mass carried by the same
        # error-feedback residual.
        from distkeras_tpu.utils.compression import parse_compress_spec

        parse_compress_spec(compress)  # validate the spec (raises early)
        self.compress = compress
        # pull_compress="bfloat16": the pulled center ships bf16-encoded
        # (half the pull bytes; matches the precision the compute path
        # already runs activations at). "int8": per-tensor symmetric
        # quarter-width (one-shot rounding, no feedback needed — pulls
        # don't accumulate; NaN/non-f32 leaves ride raw so divergence
        # and integer params survive the wire). Workers decode on
        # receipt either way.
        from distkeras_tpu.utils.compression import validate_pull_compress

        validate_pull_compress(pull_compress)
        self.pull_compress = pull_compress
        # device_resident: each worker ships its partition to HBM once and
        # streams only (W, B) index matrices per window — the async face of
        # the device-resident input path (window stream bit-identical to the
        # streamed one, so resume/dedup alignment is unchanged)
        self.device_resident = bool(device_resident)
        # every k-th commit hands worker-local state to the PS for
        # checkpoints (device-to-host copy amortization; resume replays at
        # most k-1 deduped windows per worker)
        self.worker_snapshot_stride = int(worker_snapshot_stride)
        self.mode = mode
        # remote_ps: workers reach the PS through the TCP socket protocol
        # (the cross-host/DCN path) even on one host — the full multi-host
        # wire topology, loopback-exercised (SURVEY §5.8 TPU mapping)
        self.remote_ps = bool(remote_ps)
        # standby=True: run a warm-standby PS behind the primary. The
        # primary streams its consistent snapshot + every post-dedup
        # commit to the standby (parameter_servers replication), and on
        # primary loss the standby PROMOTES; remote workers' clients carry
        # both endpoints and fail over through the shared RetryPolicy with
        # exactly-once commit resend. Implies serve_socket (replication
        # rides the socket protocol); failover needs remote_ps (in-process
        # workers hold the primary object directly — they still get the
        # replicated checkpoint/promotion machinery, not transparent
        # client failover).
        self.standby = bool(standby)
        self.serve_socket = bool(serve_socket) or self.remote_ps or self.standby
        self.parameter_server = None
        self.service = None
        self.standby_service = None
        # failover observability: client endpoint rotations and standby
        # promotions recorded across the run
        self.ps_failovers = 0
        self.ps_promotions = []
        self._failover_lock = threading.Lock()
        # checkpoint_every is in PS commits here (0 = final snapshot only)
        self._init_checkpointing(checkpoint_dir, checkpoint_every, max_to_keep)
        # fault tolerance (SURVEY §5.3): crashed worker threads are retried
        # up to worker_retries times; commit-seq dedup at the PS makes the
        # replay exactly-once. heartbeat_timeout (seconds) turns on a monitor
        # thread that flags workers gone silent.
        self.worker_retries = int(worker_retries)
        self.heartbeat_timeout = heartbeat_timeout
        # elastic=True (threads/socket modes): a partition whose worker
        # exhausts its retries is ORPHANED instead of abandoned — the
        # first surviving worker to finish its own partition adopts it,
        # re-running the dead worker OBJECT (same worker id, same commit
        # sequence), so PS dedup keeps already-landed windows exactly-
        # once. Heals time-correlated failures (an outage that outlives
        # the owner thread's retry budget but not the epoch); a worker
        # whose own state is corrupt will fail its adopter too, and the
        # partition is then recorded abandoned. No reference counterpart
        # (SURVEY §5.3 — Spark simply reschedules; here adoption must
        # thread through the PS dedup contract).
        self.elastic = bool(elastic)
        self.failures = []
        self.suspicions = []
        self.adoptions = []  # [{worker_id, adopted_by, ok}]
        self._active_workers = []  # live workers, read by the snapshot hook

    # -- template hooks -----------------------------------------------------

    def allocate_parameter_server(self):
        return self.ps_cls(self.model.params,
                           pull_compress=self.pull_compress)

    def worker_kwargs(self) -> dict:
        return {}

    def allocate_worker(self, core, worker_id, device) -> AsyncWorker:
        ps = self.parameter_server
        if self.remote_ps:
            # the retry policy paces reconnect() redials AND the client's
            # transparent in-operation failover: a worker retry often
            # races the PS host's own restart, and one refused connection
            # must not burn the whole worker_retries attempt (same
            # backoff implementation the serving client uses)
            from distkeras_tpu.networking import RetryPolicy

            endpoints = [("127.0.0.1", self.service.port)]
            if self.standby_service is not None:
                # failover pair: primary first (sticky), standby second —
                # commits carry commit_ids, so the post-failover resend is
                # exactly-once against the promoted standby's dedup table
                endpoints.append(("127.0.0.1", self.standby_service.port))
            ps = RemoteParameterServerClient(
                endpoints=endpoints,
                retry=RetryPolicy(max_attempts=8, base_delay=0.05,
                                  budget=30.0),
                on_failover=self._note_failover,
            )
        w = self.worker_cls(
            core,
            ps,
            worker_id,
            self.features_col,
            self.label_col,
            self.communication_window,
            seed=self.seed,
            device=device,
            compress=self.compress,
            **self.worker_kwargs(),
        )
        # mid-run checkpointing on: commits hand host copies of the
        # worker's local state to the PS, so periodic snapshots capture the
        # full async configuration, not just the center (VERDICT r2 weak
        # #4). With checkpoint_every=0 (final snapshot only) nothing ever
        # consumes the per-commit handoff — the end-of-run save calls
        # final_snapshot() fresh — so skip the copies entirely.
        w.keep_snapshot = self.checkpointer is not None and self.checkpoint_every > 0
        w.snapshot_stride = self.worker_snapshot_stride
        return w

    def start_service(self):
        self.parameter_server.start()
        if self.serve_socket:
            self.service = SocketParameterServer(self.parameter_server)
            self.service.start()
        if self.standby:
            # warm standby: fresh PS of the same class, synced from the
            # primary's consistent snapshot at attach (so a resumed
            # primary's restored state replicates too), then following
            # the commit stream; promotes itself on primary loss.
            # require_replicas(1) arms the durability gate on BOTH: no
            # commit is ever acked without a live replica (a brief
            # re-sync window surfaces as retriable no_replica), and the
            # promoted sole survivor relaxes its gate until a standby
            # rejoins. Remote mode ONLY: the gate's contract is that a
            # policy-paced client resend rides out the re-sync window,
            # and only RemoteParameterServerClient has that loop —
            # in-process workers commit bare, where a transient
            # no_replica would burn a whole worker_retries replay.
            standby_ps = self.allocate_parameter_server()
            if self.remote_ps:
                self.parameter_server.require_replicas(1)
                standby_ps.require_replicas(1)
            self.standby_service = SocketParameterServer(
                standby_ps,
                host="127.0.0.1",
                standby_of=("127.0.0.1", self.service.port),
                on_promote=self._on_standby_promote,
                # promotion only makes sense when workers can follow it:
                # in-process workers hold the primary OBJECT (which cannot
                # die out from under this process), so a promotion there
                # would only ever be a false positive that freezes the
                # replica — replication/durability is the whole value
                auto_promote=self.remote_ps,
            )
            self.standby_service.start()

    def stop_service(self):
        if self.standby_service is not None:
            self.standby_service.stop()
        if self.service is not None:
            self.service.stop()
            self.service = None
        self.parameter_server.stop()

    def active_parameter_server(self):
        """The PS whose state is authoritative RIGHT NOW: the promoted
        standby's after a failover, the primary's otherwise — end-of-run
        reads (final center, checkpoint snapshot, counters) must go here,
        or a run that survived a primary loss would report the dead
        primary's stale state. Remote mode only: in-process workers
        commit to the primary object until the very end, so even a
        (spurious) promotion must never outrank it."""
        if (
            self.remote_ps
            and self.standby_service is not None
            and self.standby_service.promoted
        ):
            return self.standby_service.ps
        return self.parameter_server

    def _note_failover(self, endpoint):
        with self._failover_lock:
            self.ps_failovers += 1
        if self.metrics_logger is not None:
            self.metrics_logger.log(
                event="ps_failover", endpoint=list(endpoint)
            )

    def _on_standby_promote(self, service):
        """Resume integration for the promoted standby: checkpointing
        re-attaches to the NEW primary's PS (its dedup table and worker
        snapshots rode the replication stream, so snapshots taken after
        promotion restore exactly like pre-failover ones)."""
        self.ps_promotions.append(
            {"port": service.port, "reason": service.promote_reason}
        )
        self._attach_checkpointing(service.ps)
        if self.metrics_logger is not None:
            self.metrics_logger.log(
                event="ps_promoted", port=service.port,
                reason=service.promote_reason,
            )

    # -- run ----------------------------------------------------------------

    def _attach_checkpointing(self, ps):
        """Wire per-N-commits snapshots onto the PS. The center, meta, and
        worker-state copies are all taken inside the commit's locked
        section — the checkpoint labelled n is exactly the n-update center,
        and each worker state it holds (replica params, model state,
        optimizer moments, rng, seq — handed to the PS by the committing
        worker, see ``ParameterServer.commit(local_snap=...)``) is at or
        behind that center, never ahead. A resume therefore restores a
        reachable configuration of the async system instead of a center
        with amnesiac workers (VERDICT r2 weak #4)."""
        if self.checkpointer is None:
            return

        def on_snapshot(n, center, meta, worker_snaps):
            trees = {"center": center}
            worker_states = {
                str(wid): snap
                for wid, snap in worker_snaps.items()
                if snap is not None
            }
            if worker_states:
                trees["workers"] = worker_states
            self.checkpointer.save(
                n, trees,
                {"ps_meta": meta,
                 "stream": getattr(self, "_stream_fp", None)},
            )

        ps.snapshot_every = self.checkpoint_every
        ps.on_snapshot = on_snapshot

    def _train(self, dataset, shuffle=False, resume=False):
        self.history.record_training_start()
        self.failures, self.suspicions = [], []
        core = self._make_core()
        self.parameter_server = self.allocate_parameter_server()
        # the window-stream fingerprint: resume skipping maps commit seqs
        # back to positions in a DETERMINISTIC window stream, so everything
        # that defines the stream must match the checkpoint exactly
        self._stream_fp = {
            "batch_size": self.batch_size,
            "num_workers": self.num_workers,
            "communication_window": self.communication_window,
            "seed": self.seed,
            "shuffle": bool(shuffle),
            "rows": _maybe_len(dataset),
        }
        restored_workers = {}
        if resume:
            restored = self._restore_latest()
            if restored is not None:
                _, trees, meta = restored
                saved_fp = meta.get("stream")
                if saved_fp is not None and saved_fp != self._stream_fp:
                    raise ValueError(
                        "resume config does not match the checkpoint's "
                        f"window stream: checkpoint {saved_fp}, current "
                        f"{self._stream_fp}. Resuming with a different "
                        "batch_size/num_workers/communication_window/seed/"
                        "shuffle/dataset silently misaligns the skip "
                        "positions; start fresh or restore the config."
                    )
                self.parameter_server.restore_snapshot(
                    trees["center"], meta.get("ps_meta", {})
                )
                restored_workers = trees.get("workers", {})
                # seed the PS custody table: checkpoints taken before every
                # worker's first post-resume commit keep the restored states
                self.parameter_server.restore_worker_snapshots(restored_workers)
        self._active_workers = []
        self._attach_checkpointing(self.parameter_server)
        self.start_service()
        workers = []
        try:
            parts = (dataset.shuffle(self.seed) if shuffle else dataset).partition(
                self.num_workers
            )
            devices = local_devices()
            workers = [
                self.allocate_worker(core, i, devices[i % len(devices)])
                for i in range(self.num_workers)
            ]
            for w in workers:
                snap = restored_workers.get(str(w.worker_id))
                if snap is not None:
                    w.restore_snapshot(snap)
            self._active_workers = workers

            if self.mode == "threads":
                self._warmup(core, workers[0], parts[0])
                self._run_threads(workers, parts)
            elif self.mode == "simulated":
                self._run_simulated(workers, parts)
            else:
                raise ValueError(f"unknown mode {self.mode!r}")

            for w in workers:
                self.history.extend(w.worker_id, w.records)
                for s, dt in w.timings:
                    self.history.record_window(w.worker_id, s, dt)
        finally:
            # sockets/threads must not outlive a failed train() — sweeps
            # that catch errors would otherwise accumulate leaked fds
            if self.remote_ps:
                for w in workers:
                    w.ps.close()
            self.stop_service()
        if self.checkpointer is not None:
            # the promoted standby's PS after a failover (active_parameter_
            # server): its center/meta/dedup table are the authoritative
            # continuation of the run the dead primary started
            center, meta = self.active_parameter_server().snapshot()
            trees = {"center": center}
            # workers are idle now (threads joined / schedule drained), so a
            # fresh end-of-run snapshot per worker is race-free and exact
            # even when snapshot_stride skipped the last commits
            worker_states = {}
            for w in workers:
                snap = w.final_snapshot()
                if snap is not None:
                    worker_states[str(w.worker_id)] = snap
            if worker_states:
                trees["workers"] = worker_states
            # overwrite: when the run's last commit landed exactly on a
            # checkpoint_every boundary, the periodic snapshot already owns
            # this step number but carries staler worker states
            self.checkpointer.save(
                meta.get("num_updates", 0),
                trees,
                {"ps_meta": meta, "stream": self._stream_fp},
                overwrite=True,
            )
        self.history.record_training_end()
        state = self._aggregate_worker_states(workers)
        return self._finish(self.active_parameter_server().get_params(), state)

    def _aggregate_worker_states(self, workers):
        """Mutable model state (BatchNorm moving stats) to pair with the
        center params: the elementwise mean over every worker that completed
        at least one window. Round 1 returned ``workers[0]._state``, which
        was whichever replica happened to be index 0 — and ``None`` when
        worker 0 died before its first window while others trained on
        (VERDICT r1 weak #4). Workers that never ran keep state ``None`` and
        are excluded. Falls back to the initial model state when no worker
        survives.

        Aggregation is per-leaf (VERDICT r2 weak #6 — the old version cast
        every leaf to float32 and averaged it):

        - leaves named ``aux_loss`` are transient per-step outputs (MoE load
          balance), not cross-replica statistics: the first surviving
          worker's value passes through unchanged;
        - integer / bool leaves (step counters and the like) are monotone
          progress markers, not statistics: elementwise max, dtype kept;
        - everything else (float moving statistics, e.g. BatchNorm) is the
          elementwise mean, computed in float32 and cast back to the leaf's
          own dtype.
        """
        states = [w._state for w in workers if w._state is not None]
        if not states:
            return host_copy(self.model.state)

        flat0, treedef = jax.tree_util.tree_flatten_with_path(states[0])
        flat_rest = [jax.tree_util.tree_flatten_with_path(s)[0] for s in states[1:]]

        out = []
        for i, (path, leaf) in enumerate(flat0):
            xs = [np.asarray(leaf)] + [np.asarray(f[i][1]) for f in flat_rest]
            if state_leaf_name(path) == "aux_loss":
                out.append(xs[0])
            elif xs[0].dtype.kind in ("i", "u", "b"):
                out.append(np.maximum.reduce(xs))
            else:
                mean = np.mean(
                    np.stack([x.astype(np.float32) for x in xs]), axis=0
                )
                out.append(mean.astype(xs[0].dtype))
        return jax.tree_util.tree_unflatten(treedef, out)

    def _warmup(self, core, worker, part):
        """Compile the window program before launching worker threads (the
        program dispatch lives on the worker — ``AsyncWorker.warmup`` — so
        streamed/indexed selection has exactly one owner)."""
        worker.warmup(part, self.batch_size, self.device_resident)

    def _run_threads(self, workers, parts):
        done = set()  # worker ids that exited (finished or gave up) — a
        done_lock = threading.Lock()  # completed worker is not a failure
        orphans = []  # [(worker, part)] partitions whose owner gave up

        def attempt_partition(w, part, adopted_by=None, reset_first=False):
            """Run one partition to completion with the retry budget;
            True on success. Failure records carry ``adopted_by`` when a
            survivor is re-running a dead worker's object. Every
            ``reset_for_retry`` runs INSIDE the crash boundary: in
            remote_ps mode it reconnects sockets and can itself raise
            during the very outage elastic exists for — a raise there
            must become a recorded failure, not a lost orphan or an
            exception escaping the post-join drain."""
            for attempt in range(self.worker_retries + 1):
                try:
                    if attempt > 0 or reset_first:
                        w.reset_for_retry()
                    w.train(
                        part,
                        self.batch_size,
                        num_epoch=self.num_epoch,
                        shuffle_seed=self.seed + w.worker_id,
                        device_resident=self.device_resident,
                    )
                    return True
                except Exception as e:  # noqa: BLE001 — crash boundary
                    failure = {
                        "worker_id": w.worker_id,
                        "attempt": attempt,
                        "error": repr(e),
                    }
                    if adopted_by is not None:
                        failure["adopted_by"] = adopted_by
                    self.failures.append(failure)
                    if self.metrics_logger is not None:
                        self.metrics_logger.log(
                            event="worker_failure", **failure
                        )
                    if attempt == self.worker_retries:
                        return False  # give up; others keep training

        def run(w, part):
            # ok must exist before the try: if attempt_partition itself
            # raises (e.g. metrics_logger.log failing inside its except
            # handler, or a BaseException), the adoption loop below
            # would otherwise NameError in this worker thread and the
            # partition would be lost without even being orphaned
            ok = False
            try:
                ok = attempt_partition(w, part)
                if not ok and self.elastic:
                    with done_lock:
                        orphans.append((w, part))
                    if self.metrics_logger is not None:
                        self.metrics_logger.log(
                            event="partition_orphaned", worker_id=w.worker_id
                        )
            finally:
                # mark done BEFORE any adoption: this worker will never
                # commit under its own id again, so the heartbeat
                # monitor must not suspect it while it re-runs someone
                # else's partition under the dead worker's id
                with done_lock:
                    done.add(w.worker_id)
            # elastic adoption: only a worker that FINISHED its own
            # partition adopts (a struggling worker must not pile
            # orphans onto itself).
            while ok and self.elastic and try_adopt(w.worker_id):
                pass

        def try_adopt(adopter_id):
            """Pop and re-run one orphaned partition; False when the
            queue is empty. The dead worker OBJECT re-runs — same id,
            same commit seqs, so PS dedup keeps its already-landed
            windows exactly-once. A failed adoption abandons the
            partition (no re-orphan: a second adopter would hit the same
            corrupt state, and the loop must terminate). While the
            adoption runs, the dead id leaves ``done`` so the heartbeat
            monitor watches the re-run (a hung adoption is suspectable);
            it returns on completion either way."""
            with done_lock:
                if not orphans:
                    return False
                dead_w, dead_part = orphans.pop()
                done.discard(dead_w.worker_id)
            try:
                adopted_ok = attempt_partition(
                    dead_w, dead_part, adopted_by=adopter_id,
                    reset_first=True,
                )
            finally:
                with done_lock:
                    done.add(dead_w.worker_id)
            adoption = {
                "worker_id": dead_w.worker_id,
                "adopted_by": adopter_id,
                "ok": bool(adopted_ok),
            }
            self.adoptions.append(adoption)
            if self.metrics_logger is not None:
                self.metrics_logger.log(
                    event=(
                        "partition_adopted" if adopted_ok
                        else "partition_abandoned"
                    ),
                    **adoption,
                )
            return True

        stop_monitor = threading.Event()
        monitor = None
        if self.heartbeat_timeout is not None:
            monitor = threading.Thread(
                target=self._monitor_heartbeats,
                args=(stop_monitor, done, done_lock),
                daemon=True,
            )
            monitor.start()

        threads = [
            threading.Thread(target=run, args=(w, p))
            for w, p in zip(workers, parts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # straggler orphans: a survivor that finished BEFORE the owner
        # gave up saw an empty queue and exited — drain what's left here
        # so an orphan is never silently stranded (and if every worker
        # gave up, each partition still gets one post-outage attempt)
        if self.elastic:
            while try_adopt("main"):
                pass
        stop_monitor.set()
        if monitor is not None:
            monitor.join()

    def _monitor_heartbeats(self, stop: threading.Event, done, done_lock):
        """Failure-detection loop: flag workers whose last PS pull/commit is
        older than heartbeat_timeout (absent upstream — SURVEY §5.3).
        Workers that already exited are not suspects."""
        timeout = float(self.heartbeat_timeout)
        while not stop.wait(timeout / 2):
            suspects = self.parameter_server.suspected_failures(timeout)
            with done_lock:
                suspects = [wid for wid in suspects if wid not in done]
            for wid in suspects:
                suspicion = {"worker_id": wid, "timeout": timeout}
                if suspicion not in self.suspicions:
                    self.suspicions.append(suspicion)
                    if self.metrics_logger is not None:
                        self.metrics_logger.log(
                            event="worker_suspected", **suspicion
                        )

    def _run_simulated(self, workers, parts):
        """Deterministic async: per round, begin windows in one seeded order
        and finish them in another — cross-worker staleness with an exact,
        replayable schedule."""
        queues = []
        for w, part in zip(workers, parts):
            # THE window stream definition lives on the worker
            # (iter_window_batches / iter_index_windows) — thread mode
            # consumes it directly, so reusing it here keeps cross-mode
            # determinism and the resume-skip alignment in one place. The
            # resume slice drops the windows whose commits the restored
            # center already contains (same seeded shuffles -> same stream).
            if self.device_resident:
                w.stage_resident(part)
                windows = list(
                    w.iter_index_windows(
                        self.num_epoch, self.batch_size,
                        self.seed + w.worker_id,
                    )
                )
            else:
                windows = list(
                    w.iter_window_batches(
                        part,
                        self.batch_size,
                        self.num_epoch,
                        self.seed + w.worker_id,
                    )
                )
            queues.append(windows[w._start_seq :])

        # Event-driven schedule: repeatedly pick a worker at random; begin its
        # next window if idle, else finish the in-flight one. Staleness varies
        # 0..num_workers-1 exactly as thread interleavings produce, but the
        # seed makes every run bit-identical. The schedule depends only on
        # queue lengths — identical streamed vs resident — so the two feeds
        # replay the same interleaving and the centers match bit for bit.
        rng = np.random.default_rng(self.seed)
        inflight = [False] * len(workers)
        while any(queues) or any(inflight):
            candidates = [
                i
                for i in range(len(workers))
                if inflight[i] or queues[i]
            ]
            i = int(rng.choice(candidates))
            if inflight[i]:
                workers[i].finish_window()
                inflight[i] = False
            elif self.device_resident:
                workers[i].begin_window_indexed(queues[i].pop(0))
                inflight[i] = True
            else:
                workers[i].begin_window(queues[i].pop(0))
                inflight[i] = True


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Marker base adding the async-specific knobs (reference:
    distkeras/trainers.py -> AsynchronousDistributedTrainer); the
    ``communication_window`` commit cadence lives on DistributedTrainer."""


def _reject_schedule_lr(args, kwargs, trainer_name):
    """Algorithms whose update rules consume the lr as a SCALAR (AEASGD's
    elastic force rho*lr, EAMSGD likewise, ADAG's -lr/W commit) cannot run
    a schedule — `effective_learning_rate` would freeze it at step 0, which
    for a warmup schedule is 0.0 and silently trains nothing. Fail loudly
    instead; schedules work with the other trainers. ``args`` covers the
    positional spelling (learning_rate is Trainer.__init__'s 5th
    parameter)."""
    lr = kwargs.get("learning_rate")
    if lr is None and len(args) >= 5:
        lr = args[4]
    if callable(lr):
        raise TypeError(
            f"{trainer_name} consumes the learning rate as a scalar in its "
            "update rule and does not accept schedules; pass a float (or "
            "use SingleTrainer / the sync trainer / DOWNPOUR / DynSGD, "
            "which run schedules inside the local optimizer)"
        )


class DOWNPOUR(AsynchronousDistributedTrainer):
    """Downpour-SGD (Dean et al.): workers restart from the pulled center
    every window and commit weight deltas; PS adds them
    (reference: distkeras/trainers.py -> DOWNPOUR)."""

    worker_cls = DOWNPOURWorker
    ps_cls = DeltaParameterServer


class AEASGD(AsynchronousDistributedTrainer):
    """Async Elastic Averaging SGD (reference: distkeras/trainers.py ->
    AEASGD): persistent local replicas, elastic force toward/from center."""

    worker_cls = AEASGDWorker
    ps_cls = DeltaParameterServer

    def __init__(self, *args, rho=5.0, **kwargs):
        _reject_schedule_lr(args, kwargs, type(self).__name__)
        super().__init__(*args, **kwargs)
        self.rho = float(rho)

    def worker_kwargs(self):
        return {"rho": self.rho, "learning_rate": self.learning_rate}


class EAMSGD(AEASGD):
    """Elastic averaging with (Nesterov) momentum on the local optimizer
    (reference: distkeras/trainers.py -> EAMSGD)."""

    worker_cls = EAMSGDWorker

    def __init__(self, *args, momentum=0.9, **kwargs):
        super().__init__(*args, **kwargs)
        self.momentum = float(momentum)
        self.optimizer = get_optimizer(
            "sgd", self.learning_rate, momentum=self.momentum, nesterov=True
        )
        # the installed optimizer is no longer (worker_optimizer, lr): a
        # spec that ignored the momentum/nesterov swap would collide with
        # plain-SGD trainers in the core cache and silently trade
        # optimizers (r5 review finding)
        if self._core_spec is not None:
            self._core_spec = (
                "sgd-nesterov", repr(self.learning_rate), repr(self.momentum)
            )


class ADAG(AsynchronousDistributedTrainer):
    """Accumulated Gradient Normalization (Hermans; reference:
    distkeras/trainers.py -> ADAG): commit -lr * mean-of-window gradients."""

    worker_cls = ADAGWorker
    ps_cls = ADAGParameterServer

    def __init__(self, *args, **kwargs):
        _reject_schedule_lr(args, kwargs, type(self).__name__)
        super().__init__(*args, **kwargs)

    def worker_kwargs(self):
        return {"learning_rate": self.learning_rate}


class DynSGD(AsynchronousDistributedTrainer):
    """Staleness-aware async SGD (reference: distkeras/trainers.py ->
    DynSGD): versioned PS scales commits by 1/(staleness+1)."""

    worker_cls = DynSGDWorker
    ps_cls = DynSGDParameterServer
