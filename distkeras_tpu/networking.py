"""Socket networking primitives for the cross-host (DCN) parameter-server path.

Behavioral equivalent of the reference's entire communication backend
(reference: distkeras/networking.py -> determine_host_address / connect /
send_data / recv_data): length-prefixed messages over TCP with Nagle
disabled. Two deliberate upgrades over the reference:

- payloads are serialized with the pytree/npz codec from
  ``utils.serialization`` (no pickled code objects on the wire), and
- an 8-byte big-endian length prefix replaces pickle-stream framing, so a
  message is one contiguous read.

Within one host, trainers never touch sockets — workers share the PS object
in-process. Sockets are only the DCN transport between hosts, where the
reference used them for everything.

Two robustness facilities live here because BOTH wire consumers (the PS
path and the serving tier) share them:

- :class:`RetryPolicy` — THE backoff implementation of the repo
  (exponential, full-jitter, wall-clock retry budget, server-supplied
  ``Retry-After``-style hints). ``ServingClient`` retries ``overloaded``
  replies and connection resets through it, a retried worker's
  ``ps.reconnect()`` redials through it, and the serving engine's
  supervisor paces scheduler restarts with its ``delay`` schedule — one
  implementation, so training and serving cannot drift apart on backoff
  semantics.
- ``faults.fire`` seams (``net.send`` / ``net.recv``) — the wire-level
  fault-injection hook points (socket reset mid-frame, truncated frame,
  corrupted payload, slow peer). Disarmed they are a global load and a
  ``None`` check; see ``distkeras_tpu/faults.py``.
"""

from __future__ import annotations

import random
import socket
import struct
import time

from distkeras_tpu import faults

_LEN = struct.Struct(">Q")


def determine_host_address() -> str:
    """Best-effort externally visible address of this host."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, timeout=30.0) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


class EndpointsUnreachableError(ConnectionError):
    """``connect_any`` exhausted every endpoint. ``causes`` holds the
    ``((host, port), exception)`` pairs in dial order, and the message
    names each endpoint with its own failure — a failover caller that
    only saw the LAST error used to misdiagnose a half-dead fleet (one
    refused, one timed out) as whichever endpoint happened to die last."""

    def __init__(self, causes):
        self.causes = list(causes)
        detail = "; ".join(
            f"{host}:{port}: {err!r}" for (host, port), err in self.causes
        )
        super().__init__(
            f"all {len(self.causes)} endpoints unreachable ({detail})"
        )


def connect_any(endpoints, timeout=30.0, start=0):
    """Dial a list of ``(host, port)`` endpoints in rotation starting at
    index ``start``; return ``(sock, index)`` of the first that answers.

    THE multi-endpoint dial for replicated services (the PS primary +
    warm-standby pair, the serving fleet's router): a caller that
    remembers the returned index keeps talking to the endpoint that last
    worked and only rotates onward when it dies, so failover is sticky
    rather than thrashing. Raises :class:`EndpointsUnreachableError`
    (a ``ConnectionError``) naming EVERY endpoint tried and its
    per-endpoint cause when the whole rotation refuses."""
    endpoints = list(endpoints)
    if not endpoints:
        raise ValueError("connect_any needs at least one endpoint")
    causes = []
    for k in range(len(endpoints)):
        i = (start + k) % len(endpoints)
        host, port = endpoints[i]
        try:
            return connect(host, port, timeout=timeout), i
        except OSError as e:
            causes.append(((host, port), e))
    raise EndpointsUnreachableError(causes)


def probe(endpoints, timeout=1.0):
    """Reachability sweep: dial each ``(host, port)`` once and close.
    Returns ``{(host, port): None | OSError}`` — ``None`` means the
    endpoint accepted the connection. The serving fleet's router uses
    this to cheaply re-test EJECTED replicas before spending a full
    health round-trip on them; it deliberately proves only that the
    listener answers, not that the service behind it is healthy."""
    out = {}
    for host, port in endpoints:
        try:
            sock = connect(host, port, timeout=timeout)
            try:
                sock.close()
            except OSError:
                pass
            out[(host, int(port))] = None
        except OSError as e:
            out[(host, int(port))] = e
    return out


def send_data(sock: socket.socket, payload: bytes) -> None:
    act = faults.fire("net.send", nbytes=len(payload))
    if act is not None:
        payload = _inject_send_fault(act, sock, payload)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_data(sock: socket.socket, max_len: int | None = None) -> bytes:
    """``max_len``: refuse frames whose declared length exceeds it BEFORE
    buffering a byte — on a port that accepts untrusted peers (the
    serving server), an unchecked 64-bit prefix lets one client grow
    server memory without bound."""
    faults.fire("net.recv")
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if max_len is not None and length > max_len:
        raise ValueError(
            f"incoming frame of {length} bytes exceeds the {max_len}-byte "
            "limit"
        )
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ConnectionError("socket closed mid-message")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# ------------------------------------------------------- fault behaviors


def _inject_send_fault(act: str, sock: socket.socket, payload: bytes) -> bytes:
    """Wire-level injected failures (armed ``net.send`` seams only).

    ``corrupt`` returns a mangled payload for the normal send path;
    ``truncate``/``reset`` send a partial frame themselves and raise,
    because their whole point is that the peer sees a broken stream."""
    if act == "corrupt":
        mangled = bytearray(payload)
        if mangled:
            mangled[len(mangled) // 2] ^= 0xFF
        return bytes(mangled)
    if act in ("truncate", "reset"):
        # declare the full length, deliver half: the peer's _recv_exact
        # dies mid-message either on FIN (truncate) or RST (reset)
        try:
            sock.sendall(_LEN.pack(len(payload)) + payload[: len(payload) // 2])
        except OSError:
            pass
        if act == "reset":
            try:  # SO_LINGER 0 close aborts the connection (RST, not FIN)
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
            except OSError:
                pass
        try:
            sock.close()
        except OSError:
            pass
        raise ConnectionResetError(f"injected net.send fault: {act}")
    return payload  # delay already slept inside fire(); raise already threw


# ------------------------------------------------------------ retry policy


class RetryPolicy:
    """Exponential backoff with full jitter, a bounded attempt count, and
    a wall-clock retry budget (AWS-style full jitter: each delay draws
    uniformly from ``[0, min(max_delay, base_delay * 2**attempt)]``, the
    schedule that avoids retry synchronization across many clients).

    A server hint (``Retry-After`` semantics — the ``retry_after``
    attribute the serving client attaches to ``overloaded`` errors)
    overrides the computed delay, capped at ``max_delay``.

    ``call(fn, retry_on=...)`` is the shared retry loop: it re-invokes
    ``fn`` on the listed exception types until one succeeds, the attempt
    count (``max_attempts`` total invocations) is spent, or the next
    sleep would overrun the wall-clock ``budget`` — then re-raises the
    last failure unchanged. ``seed=None`` draws real jitter; chaos tests
    pass a seed so even the sleep schedule replays."""

    def __init__(self, max_attempts: int = 5, base_delay: float = 0.05,
                 max_delay: float = 2.0, budget: float | None = 30.0,
                 seed: int | None = None):
        self.max_attempts = int(max_attempts)
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.budget = None if budget is None else float(budget)
        self._rng = random.Random(seed)

    def delay(self, attempt: int, hint: float | None = None) -> float:
        """Sleep before retry number ``attempt`` (0-based). ``hint``: a
        server-supplied seconds value (``Retry-After``) that replaces
        the jittered draw, still capped at ``max_delay``."""
        if hint is not None:
            return max(0.0, min(float(hint), self.max_delay))
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        return self._rng.uniform(0.0, cap)

    def call(self, fn, retry_on=(ConnectionError, OSError), on_retry=None):
        """Run ``fn()`` under this policy. ``on_retry(exc, attempt,
        delay)`` observes each retry (logging/counters). The hint is
        read off the exception's ``retry_after`` attribute when present
        (seconds)."""
        attempt = 0
        start = time.monotonic()
        while True:
            try:
                return fn()
            except retry_on as e:
                d = self.delay(attempt, hint=getattr(e, "retry_after", None))
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                if self.budget is not None and (
                    time.monotonic() - start + d > self.budget
                ):
                    raise
                if on_retry is not None:
                    on_retry(e, attempt, d)
                time.sleep(d)
