"""Socket networking primitives for the cross-host (DCN) parameter-server path.

Behavioral equivalent of the reference's entire communication backend
(reference: distkeras/networking.py -> determine_host_address / connect /
send_data / recv_data): length-prefixed messages over TCP with Nagle
disabled. Two deliberate upgrades over the reference:

- payloads are serialized with the pytree/npz codec from
  ``utils.serialization`` (no pickled code objects on the wire), and
- an 8-byte big-endian length prefix replaces pickle-stream framing, so a
  message is one contiguous read.

Within one host, trainers never touch sockets — workers share the PS object
in-process. Sockets are only the DCN transport between hosts, where the
reference used them for everything.
"""

from __future__ import annotations

import socket
import struct

_LEN = struct.Struct(">Q")


def determine_host_address() -> str:
    """Best-effort externally visible address of this host."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, timeout=30.0) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def send_data(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_data(sock: socket.socket, max_len: int | None = None) -> bytes:
    """``max_len``: refuse frames whose declared length exceeds it BEFORE
    buffering a byte — on a port that accepts untrusted peers (the
    serving server), an unchecked 64-bit prefix lets one client grow
    server memory without bound."""
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if max_len is not None and length > max_len:
        raise ValueError(
            f"incoming frame of {length} bytes exceeds the {max_len}-byte "
            "limit"
        )
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ConnectionError("socket closed mid-message")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
