"""Evaluation over Datasets (reference: distkeras/evaluators.py ->
AccuracyEvaluator.evaluate compares prediction vs label columns)."""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.ops.losses import get_loss


class Evaluator:
    def evaluate(self, ds: Dataset) -> float:
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction matches the label.

    ``prediction_col`` may hold class ids (from LabelIndexTransformer) or
    probability vectors (argmax is taken); ``label_col`` may be ids or
    one-hot.
    """

    def __init__(self, prediction_col="prediction", label_col="label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        pred = ds[self.prediction_col]
        if pred.ndim > 1:
            pred = np.argmax(pred, axis=-1)
        label = ds[self.label_col]
        if label.ndim > 1:
            label = np.argmax(label, axis=-1)
        return float(np.mean(pred.astype(np.int64) == label.astype(np.int64)))


class LossEvaluator(Evaluator):
    """Mean loss of a prediction column against a (one-hot) label column."""

    def __init__(self, loss="categorical_crossentropy", prediction_col="prediction", label_col="label"):
        self.loss_fn = get_loss(loss)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        import jax.numpy as jnp

        return float(
            self.loss_fn(
                jnp.asarray(ds[self.prediction_col]),
                jnp.asarray(ds[self.label_col]),
            )
        )


class RSquaredEvaluator(Evaluator):
    """Coefficient of determination R² = 1 - SS_res/SS_tot of a
    continuous prediction column against a continuous target — the
    regression counterpart of ``AccuracyEvaluator`` (the reference
    evaluated whatever its compiled Keras model emitted; reference:
    distkeras/evaluators.py). 1.0 is a perfect fit; 0.0 is the
    predict-the-mean baseline; negative is worse than that baseline."""

    def __init__(self, prediction_col="prediction", label_col="label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        pred = np.asarray(ds[self.prediction_col], np.float64).reshape(-1)
        y = np.asarray(ds[self.label_col], np.float64).reshape(-1)
        ss_res = float(np.sum((y - pred) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2))
        if ss_tot == 0.0:
            return 1.0 if ss_res == 0.0 else 0.0
        return 1.0 - ss_res / ss_tot


class PerplexityEvaluator(Evaluator):
    """Causal-LM perplexity: exp(mean next-token cross-entropy) of an LM's
    logits column against the token column. No reference counterpart
    (SURVEY §5.7: no sequence models upstream); pairs with
    ``zoo.transformer_lm`` + ``ModelPredictor`` (the prediction column
    holds (T, V) logits per row) the way AccuracyEvaluator pairs with the
    classifier families.
    """

    def __init__(self, prediction_col="prediction", label_col="label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        logits = np.asarray(ds[self.prediction_col])
        tokens = np.asarray(ds[self.label_col])
        if logits.ndim != 3 or tokens.ndim != 2:
            raise ValueError(
                "perplexity expects logits (N, T, V) and tokens (N, T); "
                f"got {logits.shape} and {tokens.shape}"
            )
        ce = LossEvaluator(
            "next_token_crossentropy", self.prediction_col, self.label_col
        ).evaluate(ds)
        return float(np.exp(ce))
