// Native data-path kernels for distkeras_tpu.
//
// The reference's data plane is Spark's JVM (DataFrames, executors'
// row iterators — reference: distkeras/workers.py minibatch assembly from
// partition iterators); this library is the TPU rebuild's native
// equivalent for the host-side input pipeline: a single-pass numeric CSV
// parser feeding float32 buffers directly (the examples' `spark.read.csv`
// load path), an order of magnitude faster than Python's csv module row
// loop, plus a row-gather primitive backing Dataset shuffling.
//
// Built as a shared library by data/native.py on first use (g++ -O3); the
// ctypes ABI below is the full surface. Fields may be double-quoted
// ("1.5"); a malformed / empty / ragged field is an error (-2), matching
// the strictness of the Python fallback.

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

char *read_file(const char *path, long *size_out) {
  FILE *f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  char *buf = static_cast<char *>(std::malloc(size + 1));
  if (!buf) {
    std::fclose(f);
    return nullptr;
  }
  long got = static_cast<long>(std::fread(buf, 1, size, f));
  std::fclose(f);
  if (got != size) {
    std::free(buf);
    return nullptr;
  }
  buf[size] = '\0';
  *size_out = size;
  return buf;
}

inline const char *line_end(const char *p, const char *end) {
  while (p < end && *p != '\n') ++p;
  return p;
}

inline bool line_is_blank(const char *p, const char *eol) {
  for (; p < eol; ++p)
    if (!std::isspace(static_cast<unsigned char>(*p))) return false;
  return true;
}

// Count columns on [p, eol), respecting double quotes.
int64_t count_cols(const char *p, const char *eol) {
  int64_t cols = 1;
  bool quoted = false;
  for (; p < eol; ++p) {
    if (*p == '"') quoted = !quoted;
    else if (*p == ',' && !quoted) ++cols;
  }
  return cols;
}

bool parse_line(const char *p, const char *eol, float *out, int64_t cols);

// Does [p, eol) look like a header line? A line is a header iff it does
// NOT parse as a full row of `cols` floats. strtof accepts nan/inf/-inf
// tokens, so a headerless file whose first data row contains them is
// correctly treated as data (the old alphabetic-character scan misdetected
// such rows as headers and silently dropped them).
bool looks_like_header(const char *p, const char *eol, int64_t cols) {
  std::vector<float> scratch(static_cast<size_t>(cols > 0 ? cols : 1));
  return !parse_line(p, eol, scratch.data(), cols);
}

// Parse one line of `cols` comma-separated floats into out. Returns true
// on success; false on empty/malformed/ragged fields. Accepts optional
// double quotes around a field. Never reads past eol, so a trailing empty
// field cannot pull values from the next line.
bool parse_line(const char *p, const char *eol, float *out, int64_t cols) {
  const char *q = p;
  for (int64_t c = 0; c < cols; ++c) {
    while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    bool quoted = q < eol && *q == '"';
    if (quoted) ++q;
    if (q >= eol || *q == ',') return false;  // empty field
    char *after = nullptr;
    float v = std::strtof(q, &after);
    if (after == q || after > eol) return false;
    out[c] = v;
    q = after;
    if (quoted) {
      if (q >= eol || *q != '"') return false;
      ++q;
    }
    while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (c + 1 < cols) {
      if (q >= eol || *q != ',') return false;  // ragged: too few fields
      ++q;
    }
  }
  while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
  return q == eol;  // ragged: extra fields
}

}  // namespace

extern "C" {

// Inspect a CSV: data-line count, column count of the first line, header
// flag. One full read; intended for introspection (the loader itself uses
// dkt_csv_load below, which parses in a single pass).
int dkt_csv_dims(const char *path, int64_t *rows, int64_t *cols,
                 int *has_header) {
  long size = 0;
  char *buf = read_file(path, &size);
  if (!buf) return -1;
  const char *end = buf + size;

  int64_t nrows = 0;
  int64_t ncols = 0;
  int header = 0;
  bool first = true;
  for (const char *p = buf; p < end;) {
    const char *eol = line_end(p, end);
    if (!line_is_blank(p, eol)) {
      ++nrows;
      if (first) {
        first = false;
        ncols = count_cols(p, eol);
        header = looks_like_header(p, eol, ncols) ? 1 : 0;
      }
    }
    p = eol < end ? eol + 1 : end;
  }
  std::free(buf);
  *rows = header ? nrows - 1 : nrows;
  *cols = ncols;
  *has_header = header;
  return 0;
}

// Single-pass load: read the file once, parse every data line into a
// malloc'd float32 buffer (*out_data, ownership passes to the caller —
// free with dkt_free). Returns 0 on success, -1 on IO error, -2 on a
// malformed/ragged line. rows/cols/has_header are outputs.
int dkt_csv_load(const char *path, float **out_data, int64_t *rows,
                 int64_t *cols, int *has_header) {
  long size = 0;
  char *buf = read_file(path, &size);
  if (!buf) return -1;
  const char *end = buf + size;

  float *data = nullptr;
  int64_t cap_rows = 0;
  int64_t nrows = 0;
  int64_t ncols = 0;
  int header = 0;
  bool first = true;
  int rc = 0;

  for (const char *p = buf; p < end;) {
    const char *eol = line_end(p, end);
    if (!line_is_blank(p, eol)) {
      if (first) {
        first = false;
        ncols = count_cols(p, eol);
        header = looks_like_header(p, eol, ncols) ? 1 : 0;
        if (header) {
          p = eol < end ? eol + 1 : end;
          continue;
        }
      }
      if (nrows == cap_rows) {
        cap_rows = cap_rows ? cap_rows * 2 : 1024;
        float *grown = static_cast<float *>(
            std::realloc(data, sizeof(float) * cap_rows * ncols));
        if (!grown) {
          rc = -1;
          break;
        }
        data = grown;
      }
      if (!parse_line(p, eol, data + nrows * ncols, ncols)) {
        rc = -2;
        break;
      }
      ++nrows;
    }
    p = eol < end ? eol + 1 : end;
  }
  std::free(buf);
  if (rc != 0) {
    std::free(data);
    return rc;
  }
  *out_data = data;
  *rows = nrows;
  *cols = ncols;
  *has_header = header;
  return 0;
}

void dkt_free(float *ptr) { std::free(ptr); }

// Row gather: dst[i] = src[idx[i]] for float32 matrices — the shuffle /
// partition materialization primitive behind Dataset.__getitem__
// (reference: distkeras/utils.py -> shuffle over DataFrames).
void dkt_gather_rows_f32(const float *src, const int64_t *idx, float *dst,
                         int64_t n_idx, int64_t row_elems) {
  for (int64_t i = 0; i < n_idx; ++i) {
    std::memcpy(dst + i * row_elems, src + idx[i] * row_elems,
                sizeof(float) * row_elems);
  }
}

}  // extern "C"
