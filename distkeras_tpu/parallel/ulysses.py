"""Ulysses-style sequence parallelism — all-to-all head-sharded attention.

The second canonical long-context scheme next to the ppermute ring
(`parallel.ring_attention`): instead of rotating K/V blocks N-1 times,
ONE `all_to_all` re-shards (batch, seq/N, heads, d) to
(batch, seq, heads/N, d), every device runs full-sequence attention over
its head slice, and a second `all_to_all` restores the token sharding
(DeepSpeed-Ulysses; no reference counterpart — SURVEY §5.7: no attention
upstream). Trade-offs vs the ring, both first-class here:

- communication: 2 all-to-alls of activation size vs N-1 K/V ppermutes —
  Ulysses wins when N is large and ICI all-to-all bandwidth is good;
  the ring wins when heads are few or attention must stay blockwise.
- constraint: num_heads must be divisible by the mesh-axis size
  (head-sharded attention); the ring has no head constraint.
- memory: each device sees the FULL sequence for its head slice —
  ``inner="blockwise"`` streams K/V blocks through the online softmax
  (`ring_attention.blockwise_attention`'s math) so score memory stays
  (seq, block) instead of (seq, seq).

Layouts match the ring exactly: (batch, seq, heads, head_dim) with the
seq axis sharded over ``axis_name``, optional ``batch_axis`` for 2-D
batch x token meshes.
"""

from __future__ import annotations

import functools

import jax

from distkeras_tpu.parallel.mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ulysses_local(q, k, v, axis_name, causal, inner, block_size):
    from distkeras_tpu.parallel.ring_attention import (
        blockwise_attention,
        dense_attention,
    )

    import jax.numpy as jnp

    # (3, b, t/N, h, d) -> (3, b, t, h/N, d): q/k/v stacked so the
    # re-shard really is ONE collective (the "2 per attention" count)
    qkv = jnp.stack((q, k, v))
    qkv = jax.lax.all_to_all(
        qkv, axis_name=axis_name, split_axis=3, concat_axis=2, tiled=True
    )
    qh, kh, vh = qkv[0], qkv[1], qkv[2]
    if inner == "blockwise":
        out = blockwise_attention(
            qh, kh, vh, causal=causal, block_size=block_size
        )
    else:
        out = dense_attention(qh, kh, vh, causal=causal)
    # (b, t, h/N, d) -> (b, t/N, h, d)
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q, k, v, mesh: Mesh, axis_name: str = "seq", causal=False,
    batch_axis=None, inner="dense", inner_block_size=512,
):
    """Attention with the sequence axis sharded over ``axis_name`` via
    head-sharding all-to-alls. Same contract as ``ring_attention``:
    q, k, v (batch, seq, heads, head_dim), seq AND num_heads both
    divisible by the axis size. ``inner`` picks the
    per-device attention over the full sequence: "dense" or "blockwise"
    (online-softmax scan, long-context memory; ``inner_block_size`` is
    its K/V block — the FULL seq length must divide it)."""
    axis_size = mesh.shape[axis_name]
    if q.shape[1] % axis_size:
        raise ValueError(
            f"seq length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name}={axis_size}"
        )
    if q.shape[2] % axis_size:
        raise ValueError(
            f"ulysses shards heads over {axis_name}: num_heads "
            f"{q.shape[2]} not divisible by {axis_size} (use the ring for "
            "head counts below the mesh size)"
        )
    if inner not in ("dense", "blockwise"):
        raise ValueError(f"inner must be 'dense' or 'blockwise'; got {inner!r}")
    spec = P(batch_axis, axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal,
            inner=inner, block_size=inner_block_size,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)


def attach_ulysses_attention(
    model, mesh: Mesh, axis_name: str = "seq", batch_axis=None,
    inner="dense", inner_block_size=512,
) -> int:
    """Point every MultiHeadSelfAttention at the Ulysses implementation
    over ``mesh``. Returns how many were attached. Process-local, like
    the ring hook (closes over a live mesh; not serialized) —
    ``ring_attention.detach_ring_attention`` removes these too."""
    from distkeras_tpu.parallel.ring_attention import attach_attention_fn

    return attach_attention_fn(
        model,
        functools.partial(
            ulysses_attention, mesh=mesh, axis_name=axis_name,
            batch_axis=batch_axis, inner=inner,
            inner_block_size=inner_block_size,
        ),
    )
