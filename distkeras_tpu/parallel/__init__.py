"""Parallelism layer: mesh construction, sharding placement, collectives.

This is the TPU-native replacement for the reference's cluster runtime
(Spark executor placement + the star-topology socket fabric, reference:
distkeras/networking.py). Sync data-parallel traffic rides ICI via XLA
collectives inside compiled programs; the async PS path stays on host.
"""

from distkeras_tpu.parallel.mesh import (
    make_mesh,
    local_devices,
    replicated_sharding,
    batch_sharding,
    shard_batch,
    replicate,
    force_cpu_mesh,
)
from distkeras_tpu.parallel.ring_attention import (
    ring_attention,
    blockwise_attention,
    attach_blockwise_attention,
    attach_ring_attention,
    detach_ring_attention,
)
from distkeras_tpu.parallel.pipeline_parallel import (
    pipeline_apply,
    stack_block_params,
    unstack_block_params,
    shard_stacked_params,
)
from distkeras_tpu.parallel.expert_parallel import (
    MoE,
    moe_ffn,
    switch_route,
    attach_expert_mesh,
    detach_expert_mesh,
    shard_moe_params,
)
