"""Parallelism layer: mesh construction, sharding placement, collectives.

This is the TPU-native replacement for the reference's cluster runtime
(Spark executor placement + the star-topology socket fabric, reference:
distkeras/networking.py). Sync data-parallel traffic rides ICI via XLA
collectives inside compiled programs; the async PS path stays on host.
"""

from distkeras_tpu.parallel.mesh import (
    make_mesh,
    local_devices,
    replicated_sharding,
    batch_sharding,
    shard_batch,
    replicate,
)
