"""Multi-host (multi-controller) runtime bootstrap.

TPU-native replacement for the role Spark's cluster runtime plays in the
reference (reference: distkeras/trainers.py -> DistributedTrainer launches
workers across executors via the Spark driver). On TPU pods there is no
driver JVM: every host runs the same program and joins a JAX distributed
coordination service; XLA collectives then ride ICI between all chips.

``initialize()`` reads the standard coordinator env vars (as emitted by
``job_deployment.Job``) or explicit kwargs and calls
``jax.distributed.initialize``. Safe to call on single-host (no-op).
"""

from __future__ import annotations

import os

ENV_COORDINATOR = "DKT_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "DKT_NUM_PROCESSES"
ENV_PROCESS_ID = "DKT_PROCESS_ID"


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the multi-host job (no-op when single-process).

    Resolution order: explicit kwargs > DKT_* env vars > single-process.
    Returns True if ``jax.distributed.initialize`` was called.
    """
    coordinator_address = coordinator_address or os.environ.get(ENV_COORDINATOR)
    if num_processes is None:
        num_processes = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
    if process_id is None:
        process_id = int(os.environ.get(ENV_PROCESS_ID, "0"))
    if coordinator_address is None or int(num_processes) <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes),
        process_id=int(process_id),
    )
    return True


def process_id() -> int:
    import jax

    return jax.process_index()


def num_processes() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    """True on the process that plays the reference's 'driver' role (rank 0
    hosts the async PS; others connect over DCN via the socket protocol)."""
    return process_id() == 0
