"""Ring attention — sequence/context parallelism over a TPU mesh axis.

The reference has no attention and no sequence dimension anywhere
(SURVEY §3.3/§5.7: MLP/CNN/tabular only), so this module has no reference
counterpart; it is the long-context capability the TPU rebuild adds so the
framework scales past single-chip sequence lengths.

Design (blockwise/ring attention, Liu et al. 2023 pattern, built from XLA
collectives rather than a port of anything):

- the sequence axis is sharded across a mesh axis (``"seq"``): every device
  holds a local block of Q, K, V;
- each device computes attention of its Q block against the K/V block it
  currently holds, accumulating with an **online softmax** (running max +
  running normalizer, so the full score matrix never materializes);
- K/V blocks rotate one hop around the ring per step via ``lax.ppermute``
  (ICI neighbor exchange — bandwidth-optimal, latency hidden behind the
  block matmuls); after ``axis_size`` steps every Q block has seen the full
  sequence.

Causal masking uses global block offsets, so device i's Q attends only to
K positions <= its own even though K blocks arrive out of order.
"""

from __future__ import annotations

import functools

import jax

from distkeras_tpu.parallel.mesh import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attention(q, k, v, acc, m, l, q_off, k_off, scale, causal):
    """One online-softmax accumulation step.

    q: (B, Tq, H, D); k, v: (B, Tk, H, D); acc: (B, Tq, H, D) f32;
    m, l: (B, H, Tq) running max / normalizer. Returns updated (acc, m, l).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(k_pos <= q_pos, s, -jnp.inf)

    m_blk = jnp.max(s, axis=-1)  # (B, H, Tq)
    m_new = jnp.maximum(m, m_blk)
    # fully-masked rows (causal, early steps) keep m == -inf; exp(-inf - -inf)
    # is nan, so guard the shift
    shift = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - shift[..., None])  # (B, H, Tq, Tk)
    correction = jnp.exp(jnp.where(jnp.isneginf(m), shift, m) - shift)
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32)
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return acc_new, m_new, l_new


def _init_carry(b, tq, h, d):
    """Fresh online-softmax carry: zero accumulator, -inf running max,
    zero normalizer. Shared by the ring body and blockwise_attention."""
    return (
        jnp.zeros((b, tq, h, d), jnp.float32),
        jnp.full((b, h, tq), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, tq), jnp.float32),
    )


def _finalize(acc, l, out_dtype):
    """Normalize the accumulator. Rows with no visible keys (can't happen
    for causal self-attn since a position always sees itself) keep the
    division safe."""
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None].transpose(0, 2, 1, 3)).astype(out_dtype)


def _ring_attention_local(q, k, v, axis_name, axis_size, scale, causal):
    """Per-device body (runs under shard_map): rotate K/V around the ring."""
    my_idx = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    acc, m, l = _init_carry(b, tq, h, d)
    q_off = my_idx * tq

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for step in range(axis_size):
        src_idx = (my_idx - step) % axis_size  # whose block we hold now
        acc, m, l = _block_attention(
            q, k, v, acc, m, l, q_off, src_idx * k.shape[1], scale, causal
        )
        if step + 1 < axis_size:
            k, v = jax.lax.ppermute((k, v), axis_name, perm)

    return _finalize(acc, l, q.dtype)


def ring_attention(
    q, k, v, mesh: Mesh, axis_name: str = "seq", causal=False, batch_axis=None
):
    """Multi-head attention with the sequence axis sharded over ``axis_name``.

    q, k, v: (batch, seq, heads, head_dim), seq divisible by the axis size.
    Returns (batch, seq, heads, head_dim) with the same sharding.

    ``batch_axis``: optional mesh axis the BATCH dim is sharded over (2-D
    data x sequence parallelism). Attention is independent per batch
    element, so the ring body is unchanged — each data slice runs its own
    ring over ``axis_name``; the spec just keeps the batch shards in place
    instead of forcing an all-gather.
    """
    axis_size = mesh.shape[axis_name]
    if q.shape[1] % axis_size:
        raise ValueError(
            f"seq length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name}={axis_size}"
        )
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(batch_axis, axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            axis_size=axis_size,
            scale=scale,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)


def blockwise_attention(q, k, v, causal=False, block_size=512):
    """Single-device memory-efficient attention: ``lax.scan`` over K/V
    blocks with the same online softmax the ring uses (`_block_attention`),
    so the full (Tq, Tk) score matrix never materializes — peak score
    memory is (Tq, block_size). The single-chip face of the long-context
    design: past one chip, shard the sequence and use :func:`ring_attention`
    (same accumulation math, blocks arriving over ICI instead of a scan).

    q, k, v: (batch, seq, heads, head_dim); seq divisible by ``block_size``
    (pass a smaller block for short sequences, e.g. tests). Matches
    :func:`dense_attention` numerically.
    """
    b, t, h, d = q.shape
    if k.shape[1] != t or v.shape[1] != t:
        # Self-attention only: the block reshape below derives the K/V block
        # count from q's length, and the causal offsets assume Tq == Tk.
        # With Tq <= block_size this used to silently hit the dense path
        # (correct) but blow up in the reshape past it (ADVICE r2 #1).
        raise ValueError(
            "blockwise_attention is self-attention only: expected "
            f"k/v seq length {t} (q's), got k={k.shape[1]}, v={v.shape[1]}"
        )
    if t <= block_size:  # one (possibly partial) block IS the dense case
        return dense_attention(q, k, v, causal=causal)
    if t % block_size:
        raise ValueError(
            f"seq length {t} not divisible by block_size {block_size}"
        )
    nb = t // block_size
    scale = 1.0 / (d**0.5)
    # (nb, B, block, H, D) so scan slices one K/V block per step
    kb = jnp.moveaxis(k.reshape(b, nb, block_size, h, d), 1, 0)
    vb = jnp.moveaxis(v.reshape(b, nb, block_size, h, d), 1, 0)
    offs = jnp.arange(nb, dtype=jnp.int32) * block_size

    def step(carry, xs):
        acc, m, l = carry
        k_blk, v_blk, k_off = xs
        acc, m, l = _block_attention(
            q, k_blk, v_blk, acc, m, l, 0, k_off, scale, causal
        )
        return (acc, m, l), None

    # the init carry derives from q (not fresh zeros) so that under
    # shard_map it inherits q's varying manual axes — a replicated init
    # vs a varying output fails lax.scan's carry-type check when this
    # runs as the Ulysses inner attention
    acc0 = (q * 0.0).astype(jnp.float32)
    row0 = jnp.swapaxes(acc0[..., 0], 1, 2)  # (B, H, Tq) of zeros
    init = (acc0, row0 - jnp.inf, row0)
    (acc, m, l), _ = jax.lax.scan(step, init, (kb, vb, offs))
    return _finalize(acc, l, q.dtype)


def attach_attention_fn(model, fn) -> int:
    """The one attach loop shared by every attention hook (blockwise,
    ring, ulysses, flash): point every MultiHeadSelfAttention's
    ``attention_fn`` at ``fn``; returns how many were attached. All such
    hooks are process-local and not serialized."""
    from distkeras_tpu.models.layers import MultiHeadSelfAttention
    from distkeras_tpu.models.sequential import walk_layers

    n = 0
    for layer in walk_layers(model):
        if isinstance(layer, MultiHeadSelfAttention):
            layer.attention_fn = fn
            n += 1
    return n


def attach_blockwise_attention(model, block_size=512) -> int:
    """Point every MultiHeadSelfAttention at :func:`blockwise_attention`
    (single-device long-context mode). Returns how many were attached.
    Unlike the ring hook this closes over no mesh, but it is still a
    process-local hook and is not serialized."""
    return attach_attention_fn(
        model, functools.partial(blockwise_attention, block_size=block_size)
    )


def attach_ring_attention(
    model, mesh: Mesh, axis_name: str = "seq", batch_axis=None
) -> int:
    """Walk a model's layers and point every MultiHeadSelfAttention at the
    ring implementation over ``mesh``. Returns how many were attached.
    (Process-local: hooks close over the live mesh and are not serialized —
    re-attach after deserializing on another host.)"""
    return attach_attention_fn(
        model,
        functools.partial(
            ring_attention, mesh=mesh, axis_name=axis_name,
            batch_axis=batch_axis,
        ),
    )


def detach_ring_attention(model) -> int:
    """Remove ring-attention hooks installed by ``attach_ring_attention``:
    every MultiHeadSelfAttention reverts to dense attention. Returns how
    many hooks were removed. Trainers call this when training ends so
    neither the caller's model nor the returned copy keeps a closure over a
    live (process-local) Mesh."""
    from distkeras_tpu.models.layers import MultiHeadSelfAttention
    from distkeras_tpu.models.sequential import walk_layers

    count = 0
    for layer in walk_layers(model):
        if (
            isinstance(layer, MultiHeadSelfAttention)
            and layer.attention_fn is not None
        ):
            layer.attention_fn = None
            count += 1
    return count


def dense_attention(q, k, v, causal=False):
    """Single-device reference: plain softmax attention, same layout."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1) <= (
            jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        )
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
