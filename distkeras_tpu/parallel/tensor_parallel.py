"""Tensor parallelism (TP) — shard parameters over a "model" mesh axis.

The reference has no model sharding of any kind (SURVEY §3.3: weights are
fully replicated; the model must fit one worker). TP is the TPU rebuild's
stretch capability for models that don't: Dense/conv kernels shard their
output dimension across the ``"model"`` axis and XLA's GSPMD partitioner
inserts the activation collectives — no per-layer communication code, the
sharding annotations ARE the parallelism (scaling-book recipe: pick a
mesh, annotate, let XLA insert collectives).

Composes with the sync data-parallel trainer: a 2-D ``Mesh(("data",
"model"))`` shards batches over "data" (gradient psum) and parameters over
"model" (activation all-gather/reduce-scatter), both over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel.mesh import local_devices


def make_dp_tp_mesh(data_parallel: int, model_parallel: int, devices=None) -> Mesh:
    """2-D mesh: ``data_parallel * model_parallel`` devices as
    ("data", "model")."""
    n = data_parallel * model_parallel
    devs = devices if devices is not None else local_devices(n)
    return Mesh(
        np.array(devs[:n]).reshape(data_parallel, model_parallel),
        ("data", "model"),
    )


def leaf_partition_spec(shape, axis_size, axis_name="model", min_elems=2):
    """Sharding rule for one parameter leaf: shard the trailing (output)
    dimension over the model axis when divisible, else replicate.

    Covers Dense kernels (in, out), conv kernels (H, W, in, out), and
    matching bias vectors (out,) so layer outputs and their biases carry
    the same sharding.
    """
    if len(shape) >= 2 and shape[-1] % axis_size == 0 and shape[-1] >= min_elems:
        return P(*([None] * (len(shape) - 1)), axis_name)
    if len(shape) == 1 and shape[0] % axis_size == 0 and shape[0] >= min_elems:
        return P(axis_name)
    return P()


def shard_params(params, mesh: Mesh, axis_name: str = "model"):
    """Place a parameter pytree on the mesh with TP shardings."""
    axis_size = mesh.shape[axis_name]

    def place(leaf):
        spec = leaf_partition_spec(np.shape(leaf), axis_size, axis_name)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params)


def describe_shardings(params, mesh: Mesh, axis_name: str = "model"):
    """{path: spec} map — introspection/tests."""
    axis_size = mesh.shape[axis_name]
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return {
        jax.tree_util.keystr(path): leaf_partition_spec(
            np.shape(leaf), axis_size, axis_name
        )
        for path, leaf in flat
    }


# -- serving-tier (decode) placement ------------------------------------------
#
# The trainer's rule above (shard every trailing dim) is wrong for a
# decode step: sharding BOTH matmuls of a pair column-wise leaves the
# activations sharded between them and XLA inserts an all-gather per
# layer. Decode wants the classic Megatron pairing instead — attention
# QKV column-sharded (equivalently: HEAD-sharded, since the out dim is
# heads x head_dim) with the output projection ROW-sharded, and MLP
# fc1 column / fc2 row — so each block runs shard-local until one
# psum at each pair's row matmul returns the activations to
# replicated. Embeddings, layer norms, and the vocab head replicate
# (they are a rounding error of the weight bytes decode streams).
# MoE expert stacks shard their expert dim over the SAME serving axis
# via ``expert_parallel.moe_group_specs`` — decode-time expert
# parallelism rides the one mesh.


def _pair_specs(spec, leaf, axis_size, axis_name):
    """Resolve ``spec`` ("col" | "row") for one weight leaf, handling
    the quantized forms: an int8 ``{"q", "s"}`` group shards ``q``
    like the f32 matrix would (the per-output-column scales follow a
    column shard, replicate under a row shard); a packed
    ``Int4Weight`` replicates (its two-values-per-byte IN-dim packing
    does not split cleanly over a row shard, and a half-sharded int4
    matrix is not worth a special program). Non-divisible dims
    replicate rather than raise — head divisibility, the one
    correctness-critical constraint, is validated loudly by the engine
    before placement ever runs."""
    from distkeras_tpu.ops.quantization import Int4Weight

    if isinstance(leaf, Int4Weight):
        return P()
    mat = leaf["q"] if isinstance(leaf, dict) else leaf
    shape = np.shape(mat)
    if len(shape) != 2:
        return P()
    d = shape[1] if spec == "col" else shape[0]
    if d % axis_size or d < axis_size:
        return P()
    qspec = P(None, axis_name) if spec == "col" else P(axis_name, None)
    if isinstance(leaf, dict):  # int8 {"q", "s"}
        return {"q": qspec, "s": P(axis_name) if spec == "col" else P()}
    return qspec


def decode_param_specs(params, axis_size: int, axis_name: str = "model"):
    """Partition specs for a causal-LM param tree under serving tensor
    parallelism — the structure-matched tree ``shard_decode_params``
    places and tests/docs introspect. Returns a pytree shaped like
    ``params`` whose leaves are ``PartitionSpec`` (quantized int8
    groups expand to per-field specs)."""
    from distkeras_tpu.parallel.expert_parallel import (
        is_moe_group,
        moe_group_specs,
    )

    def vec_spec(leaf):
        n = np.shape(leaf)
        if len(n) == 1 and n[0] % axis_size == 0 and n[0] >= axis_size:
            return P(axis_name)
        return P()

    def group(node, kind):
        out = {}
        for k, v in node.items():
            if kind == "mhsa" and k in ("wq", "wk", "wv"):
                out[k] = _pair_specs("col", v, axis_size, axis_name)
            elif kind == "mhsa" and k == "wo":
                out[k] = _pair_specs("row", v, axis_size, axis_name)
            elif kind == "fc1" and k == "kernel":
                out[k] = _pair_specs("col", v, axis_size, axis_name)
            elif kind == "fc1" and k == "bias":
                out[k] = vec_spec(v)
            elif kind == "fc2" and k == "kernel":
                out[k] = _pair_specs("row", v, axis_size, axis_name)
            else:
                out[k] = P()  # bo, fc2 bias, anything unrecognized
        return out

    def moe_specs(node):
        tmpl = moe_group_specs(axis_name)
        out = {}
        for k, v in node.items():
            spec = tmpl.get(k, P())
            if spec != P():
                e = np.shape(v)[0] if np.ndim(v) else 0
                if e % axis_size or e < axis_size:
                    spec = P()
            out[k] = spec
        return out

    def walk(node):
        if is_moe_group(node):
            return moe_specs(node)
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k in ("mhsa", "fc1", "fc2") and isinstance(v, dict):
                    out[k] = group(v, k)
                else:
                    out[k] = walk(v)
            return out
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return P()  # embeddings, LN, head, biases: replicated

    return walk(params)


def shard_decode_params(params, mesh: Mesh, axis_name: str = "model"):
    """Place a causal-LM param tree for serving decode: Megatron-paired
    attention/MLP shards (see the module note above), MoE expert stacks
    expert-sharded over the same axis, everything else replicated.
    Returns a NEW placed tree — the caller's tree (the trainable f32
    master, the predict path's copy) is untouched."""
    specs = decode_param_specs(params, mesh.shape[axis_name], axis_name)

    def walk(node, spec):
        # NOTE: PartitionSpec is a tuple subclass on some JAX versions,
        # so the P check must come before any tuple/list branch
        if isinstance(spec, P):
            return jax.device_put(node, NamedSharding(mesh, spec))
        if isinstance(node, dict):
            return {k: walk(node[k], spec[k]) for k in node}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v, s) for v, s in zip(node, spec))
        return jax.device_put(node, NamedSharding(mesh, P()))

    return walk(params, specs)


def describe_decode_shardings(params, mesh: Mesh,
                              axis_name: str = "model"):
    """{dotted path: spec} over ``decode_param_specs`` — tests/docs."""
    specs = decode_param_specs(params, mesh.shape[axis_name], axis_name)
    out = {}

    def walk(node, path):
        if isinstance(node, P):  # before tuple: P subclasses tuple
            out[path] = node
        elif isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{path}/{k}" if path else k)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{path}/{i}")
        else:
            out[path] = node

    walk(specs, "")
    return out
