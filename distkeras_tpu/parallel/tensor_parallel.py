"""Tensor parallelism (TP) — shard parameters over a "model" mesh axis.

The reference has no model sharding of any kind (SURVEY §3.3: weights are
fully replicated; the model must fit one worker). TP is the TPU rebuild's
stretch capability for models that don't: Dense/conv kernels shard their
output dimension across the ``"model"`` axis and XLA's GSPMD partitioner
inserts the activation collectives — no per-layer communication code, the
sharding annotations ARE the parallelism (scaling-book recipe: pick a
mesh, annotate, let XLA insert collectives).

Composes with the sync data-parallel trainer: a 2-D ``Mesh(("data",
"model"))`` shards batches over "data" (gradient psum) and parameters over
"model" (activation all-gather/reduce-scatter), both over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel.mesh import local_devices


def make_dp_tp_mesh(data_parallel: int, model_parallel: int, devices=None) -> Mesh:
    """2-D mesh: ``data_parallel * model_parallel`` devices as
    ("data", "model")."""
    n = data_parallel * model_parallel
    devs = devices if devices is not None else local_devices(n)
    return Mesh(
        np.array(devs[:n]).reshape(data_parallel, model_parallel),
        ("data", "model"),
    )


def leaf_partition_spec(shape, axis_size, axis_name="model", min_elems=2):
    """Sharding rule for one parameter leaf: shard the trailing (output)
    dimension over the model axis when divisible, else replicate.

    Covers Dense kernels (in, out), conv kernels (H, W, in, out), and
    matching bias vectors (out,) so layer outputs and their biases carry
    the same sharding.
    """
    if len(shape) >= 2 and shape[-1] % axis_size == 0 and shape[-1] >= min_elems:
        return P(*([None] * (len(shape) - 1)), axis_name)
    if len(shape) == 1 and shape[0] % axis_size == 0 and shape[0] >= min_elems:
        return P(axis_name)
    return P()


def shard_params(params, mesh: Mesh, axis_name: str = "model"):
    """Place a parameter pytree on the mesh with TP shardings."""
    axis_size = mesh.shape[axis_name]

    def place(leaf):
        spec = leaf_partition_spec(np.shape(leaf), axis_size, axis_name)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params)


def describe_shardings(params, mesh: Mesh, axis_name: str = "model"):
    """{path: spec} map — introspection/tests."""
    axis_size = mesh.shape[axis_name]
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return {
        jax.tree_util.keystr(path): leaf_partition_spec(
            np.shape(leaf), axis_size, axis_name
        )
        for path, leaf in flat
    }
