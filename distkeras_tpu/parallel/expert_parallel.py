"""Expert parallelism — switch-routed mixture-of-experts over a mesh axis.

No reference counterpart (SURVEY §3.3: EP absent upstream); this is the
TPU-rebuild capability that completes the parallelism axes (DP / TP / SP /
PP / EP). Built the Mesh-TensorFlow/GSPMD way rather than with manual
point-to-point routing:

- ``switch_route`` computes top-1 routing with a fixed per-expert
  **capacity** (static shapes — an XLA requirement; overflowing tokens are
  dropped by the dispatch mask and pass through the residual);
- dispatch/combine are one-hot einsums: tokens (S, D) -> expert batches
  (E, C, D) and back. Under ``jit`` over a mesh with an ``"expert"`` axis,
  the expert-stacked FFN params and the (E, C, D) intermediate carry a
  ``P("expert")`` sharding — **XLA inserts the all-to-all** between the
  token-sharded and expert-sharded layouts; nothing here speaks collectives
  directly (SURVEY's "let GSPMD insert the collectives" recipe);
- the auxiliary load-balance loss (Shazeer/Fedus switch loss: E * sum of
  fraction-routed x mean-router-prob) is returned for the trainer to add.

``MoE`` is the layer-zoo wrapper (drop-in FFN replacement);
``shard_moe_params`` places a built model's expert stacks over the mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.layers import Layer, register_layer, _glorot_uniform


def switch_route(router_logits, capacity: int):
    """Top-1 (switch) routing with fixed capacity.

    router_logits: (S, E). Returns (dispatch (S, E, C) one-hot, combine
    (S, E, C) gate-weighted, aux_loss scalar).
    """
    s, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (S,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    expert_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (S, E)
    # position of each token within its expert's queue (exclusive cumsum)
    position = jnp.cumsum(expert_onehot, axis=0) * expert_onehot - expert_onehot
    keep = (position < capacity).astype(jnp.float32) * expert_onehot  # (S, E)
    pos_onehot = jax.nn.one_hot(
        position.sum(axis=-1).astype(jnp.int32), capacity, dtype=jnp.float32
    )  # (S, C)
    dispatch = keep[:, :, None] * pos_onehot[:, None, :]  # (S, E, C)
    combine = dispatch * gate[:, None, None]

    # switch load-balance loss: E * sum_e f_e * p_e
    fraction = expert_onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux_loss = e * jnp.sum(fraction * mean_prob)
    return dispatch, combine, aux_loss


def moe_ffn(params, x, capacity_factor=1.25, mesh=None, axis_name="expert"):
    """Switch-MoE feed-forward over tokens.

    params: {"router": (D, E), "wi": (E, D, H), "wo": (E, H, D)}.
    x: (..., D) — leading axes are flattened into the token axis.
    Returns (same shape as x, aux_loss).
    """
    d = x.shape[-1]
    lead = x.shape[:-1]
    tokens = x.reshape(-1, d)
    s = tokens.shape[0]
    e = params["router"].shape[1]
    capacity = max(1, int(capacity_factor * s / e))

    logits = tokens.astype(jnp.float32) @ params["router"]
    dispatch, combine, aux = switch_route(logits, capacity)

    expert_in = jnp.einsum(
        "sec,sd->ecd", dispatch.astype(x.dtype), tokens
    )  # (E, C, D)
    if mesh is not None:
        # pin the expert-major layout; GSPMD inserts the token<->expert
        # all-to-all around this constraint
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(axis_name))
        )
    h = jnp.einsum("ecd,edh->ech", expert_in, params["wi"].astype(x.dtype))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["wo"].astype(x.dtype))
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(axis_name))
        )
    out = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), expert_out)
    return out.reshape(*lead, d), aux


@register_layer
class MoE(Layer):
    """Mixture-of-experts FFN layer (drop-in Dense-pair replacement).

    ``attach_expert_mesh`` points the layer at a live mesh so the expert
    dimension shards; without a mesh it computes the identical math on one
    device. Each forward writes the switch load-balance loss to
    ``state["aux_loss"]``; ``WorkerCore`` sums every ``aux_loss`` leaf into
    the training loss with weight ``aux_loss_weight`` (Trainer kwarg,
    default 0.01), so routing IS regularized by every shipped trainer.
    """

    def __init__(self, num_experts, hidden_ratio=4, capacity_factor=1.25):
        self.num_experts = int(num_experts)
        self.hidden_ratio = int(hidden_ratio)
        self.capacity_factor = float(capacity_factor)
        self.mesh = None  # process-local hook, like ring attention's
        self.axis_name = "expert"

    def init(self, rng, in_shape):
        d = in_shape[-1]
        h = self.hidden_ratio * d
        ks = jax.random.split(rng, 3)
        params = {
            "router": _glorot_uniform(ks[0], (d, self.num_experts), d,
                                      self.num_experts),
            "wi": 0.02 * jax.random.normal(
                ks[1], (self.num_experts, d, h), jnp.float32
            ),
            "wo": 0.02 * jax.random.normal(
                ks[2], (self.num_experts, h, d), jnp.float32
            ),
        }
        return params, {"aux_loss": jnp.zeros((), jnp.float32)}, in_shape

    def apply(self, params, state, x, train=False, rng=None):
        out, aux = moe_ffn(
            params,
            x,
            capacity_factor=self.capacity_factor,
            mesh=self.mesh,
            axis_name=self.axis_name,
        )
        return x + out, {"aux_loss": aux}

    def get_config(self):
        return {
            "layer": "MoE",
            "num_experts": self.num_experts,
            "hidden_ratio": self.hidden_ratio,
            "capacity_factor": self.capacity_factor,
        }


def attach_expert_mesh(model, mesh: Mesh, axis_name: str = "expert") -> int:
    """Point every MoE layer in ``model`` at ``mesh`` (sharded experts).
    Returns how many layers were attached. Process-local, like
    ``ring_attention.attach_ring_attention``."""
    from distkeras_tpu.models.sequential import walk_layers

    axis_size = mesh.shape[axis_name]
    count = 0
    for layer in walk_layers(model):
        if isinstance(layer, MoE):
            if layer.num_experts % axis_size:
                raise ValueError(
                    f"num_experts={layer.num_experts} is not divisible by "
                    f"mesh axis {axis_name}={axis_size}"
                )
            layer.mesh = mesh
            layer.axis_name = axis_name
            count += 1
    return count


def detach_expert_mesh(model) -> int:
    """Remove mesh hooks installed by :func:`attach_expert_mesh`."""
    from distkeras_tpu.models.sequential import walk_layers

    count = 0
    for layer in walk_layers(model):
        if isinstance(layer, MoE) and layer.mesh is not None:
            layer.mesh = None
            count += 1
    return count


def is_moe_group(node) -> bool:
    """Whether ``node`` is an MoE param group — {"router", "wi", "wo"},
    the layout ``MoE.init`` emits. STRUCTURAL detection, shared by the
    training placement below and the serving-tier decode placement
    (``tensor_parallel.decode_param_specs``): other layers also name
    weights ``wo`` (TransformerBlock's attention output projection),
    and sharding those over the expert axis would be wrong."""
    return isinstance(node, dict) and {"router", "wi", "wo"} <= set(node)


def moe_group_specs(axis_name: str = "expert") -> dict:
    """Partition specs for one MoE param group: the (E, ...) expert
    stacks shard their leading (expert) dim over ``axis_name``, the
    router replicates. The serving tier reuses this with its own axis
    name ("model"): at decode time the expert FFNs route through the
    same placement the training tier uses, just over the serving mesh."""
    return {"router": P(), "wi": P(axis_name), "wo": P(axis_name)}


def shard_moe_params(params, mesh: Mesh, axis_name: str = "expert"):
    """Place a built model's params with every MoE expert stack sharded
    over ``axis_name``; everything else replicated.

    An expert stack is identified structurally via :func:`is_moe_group`
    — see its docstring for why leaf names alone are not enough."""
    repl = NamedSharding(mesh, P())
    exp = NamedSharding(mesh, P(axis_name))

    def place_tree(node):
        if node is None:
            return None  # no-param convention: zero leaves, nothing to place
        if is_moe_group(node):
            return {
                k: jax.device_put(v, exp if k in ("wi", "wo") else repl)
                for k, v in node.items()
            }
        if isinstance(node, dict):
            return {k: place_tree(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(place_tree(v) for v in node)
        if not jax.tree_util.all_leaves([node]):
            # an unrecognized pytree container could hide an expert stack;
            # fail loudly rather than silently replicating it
            raise TypeError(
                "shard_moe_params only understands dict/list/tuple param "
                f"trees; got container {type(node).__name__}"
            )
        return jax.device_put(node, repl)

    return place_tree(params)
