"""Backend selection that survives a dead accelerator transport.

The reference delegated platform choice to Spark executor config; here the
platform is JAX's, and on this sandbox the TPU arrives through an `axon`
network tunnel that is frequently down. A failed in-process backend init
is sticky (the plugin can hang JAX's first device query for minutes), so
the only safe probe is OUT OF PROCESS: try `jax.devices()` in a
subprocess under a hard timeout, and pin whichever platform survived
before this process ever touches the backend.

Shared by the bench harnesses (`bench.py` re-exports these names) and by
every example script, so `python examples/mnist.py` works in any tunnel
state: healthy -> real TPU, dead -> the virtual CPU mesh, no hang.
"""

from __future__ import annotations

import subprocess
import sys


def _probe_src(config_platform: str | None) -> str:
    pin = (
        f"jax.config.update('jax_platforms', {config_platform!r}); "
        if config_platform
        else ""
    )
    return (
        "import jax; "
        f"{pin}d = jax.devices(); print('PLATFORM=' + d[0].platform)"
    )


def _probe_backend(config_platform: str | None, timeout: float) -> str | None:
    """Try initializing JAX in a subprocess; return the platform name on
    success, None on failure/hang. Probing out-of-process matters because a
    failed in-process backend init is sticky (VERDICT r1 weak #1: the axon
    plugin can hang unless the platform is pinned before any backend touch).
    The cpu pin uses ``jax.config.update`` rather than ``JAX_PLATFORMS``
    because the sandbox's sitecustomize registers its TPU plugin in a way
    that overrides the env var (same approach as tests/conftest.py)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _probe_src(config_platform)],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if out.returncode != 0:
        return None
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return None


def resolve_backend() -> tuple[str, str | None] | None:
    """Pick a working backend before importing jax in-process.

    Returns (platform, config_pin): apply ``jax.config.update('jax_platforms',
    config_pin)`` after import when config_pin is not None."""
    candidates = [
        (None, 75.0),  # whatever the driver set (axon TPU when healthy)
        ("cpu", 60.0),  # always-available fallback
    ]
    for config_platform, timeout in candidates:
        platform = _probe_backend(config_platform, timeout)
        if platform is not None:
            return platform, config_platform
    return None


def setup_backend(
    cpu: bool = False,
    cpu_devices: int = 1,
    fallback_cpu_devices: int | None = None,
) -> str:
    """The bootstrap shared by the bench harnesses and the examples: force
    a ``cpu_devices``-wide CPU mesh when asked, otherwise probe
    out-of-process (a dead tunnel must not hang in-process init) and pin
    the surviving platform. Returns the platform string.

    ``fallback_cpu_devices`` widens the CPU mesh when the probe falls back
    to CPU (examples pass their worker count so `--workers 8` on a dead
    tunnel still exercises an 8-device virtual mesh); the bench harnesses
    leave it None — their CPU fallback measures a single device."""
    # NOTE on import safety: importing this module already ran the package
    # __init__ (and so imported jax) — that is fine because importing jax
    # does not initialize a backend; only a device query does, and the
    # probe above runs in a SUBPROCESS. The lazy import here just keeps
    # the function's dependencies local.
    from distkeras_tpu.parallel.mesh import force_cpu_mesh

    if cpu:
        force_cpu_mesh(cpu_devices)
        return "cpu"
    resolved = resolve_backend()
    if resolved is None:
        raise SystemExit("no JAX backend could be initialized")
    platform, config_pin = resolved
    if platform == "cpu" and fallback_cpu_devices:
        force_cpu_mesh(fallback_cpu_devices)
        return platform
    import jax

    if config_pin is not None:
        jax.config.update("jax_platforms", config_pin)
    return platform
