"""Device mesh + sharding placement helpers.

The sync trainer's entire communication story (replacing the reference's
pull/commit socket protocol, reference: distkeras/parameter_servers.py ->
SocketParameterServer) is: params replicated over a 1-D ``Mesh(("data",))``,
batches sharded along "data", loss averaged over the global batch inside
``jit`` — XLA inserts the gradient ``psum`` over ICI automatically.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# THE shard_map accessor for the whole repo: ``jax.shard_map`` only became
# a public top-level name in newer JAX; older installs keep it under
# ``jax.experimental.shard_map`` with the same (f, mesh, in_specs,
# out_specs) signature. Every parallel module routes through this alias so
# the version probe lives in exactly one place.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed JAX
    from jax.experimental.shard_map import shard_map  # noqa: F401


def local_devices(n=None):
    devs = jax.devices()
    if n is None:
        return devs
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return devs[:n]


def make_mesh(num_devices=None, axis_names=("data",), devices=None) -> Mesh:
    """1-D (default) or n-D mesh over the first ``num_devices`` devices."""
    devs = devices if devices is not None else local_devices(num_devices)
    n = len(devs)
    if len(axis_names) == 1:
        shape = (n,)
    else:
        # factor n into len(axis_names) axes, largest-first
        shape = []
        rem = n
        for _ in axis_names[:-1]:
            f = _largest_factor(rem)
            shape.append(f)
            rem //= f
        shape.append(rem)
        shape = tuple(shape)
    return Mesh(np.array(devs).reshape(shape), axis_names)


def _largest_factor(n):
    for f in range(int(n**0.5), 0, -1):
        if n % f == 0:
            return max(f, n // f)
    return n


def force_cpu_mesh(num_devices: int = 8) -> None:
    """Pin a ``num_devices``-virtual-device CPU platform. Must run before
    the JAX backend initializes (the forced host device count is read from
    XLA_FLAGS at backend init, and the platform pin must be a config update
    because env-var selection can be overridden by pre-registered plugins).
    This is the one supported way to exercise multi-device code paths
    without accelerator hardware — tests/conftest.py and every example's
    ``--cpu`` flag route through the same mechanism."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={int(num_devices)}"
        ).strip()
    jax.config.update("jax_platforms", "cpu")


def serving_mesh(spec, *, devices=None, axis_name: str = "model") -> Mesh:
    """THE serving-mesh constructor: every consumer (``ServingEngine``,
    the decode bench, the soak, the tests) resolves its tensor-parallel
    mesh here instead of re-rolling ``Mesh(jax.devices()[:n], ...)``.

    ``spec`` forms:

    - ``"tp:N"`` — N-way tensor parallelism over the first N devices
      (``devices`` overrides the pool);
    - an int ``N`` — same as ``"tp:N"``;
    - a ``jax.sharding.Mesh`` — passed through after validating it
      carries ``axis_name`` (an engine cannot shard over an axis its
      partition specs never name).

    Validation is LOUD and happens at construction (bundle load), not
    at the first decode step: asking for more ways than there are
    devices raises ``ValueError`` naming both numbers, so a misplaced
    replica fails its boot health-check instead of wedging later.
    """
    if isinstance(spec, Mesh):
        if axis_name not in spec.axis_names:
            raise ValueError(
                f"serving mesh must carry a {axis_name!r} axis; got "
                f"axes {spec.axis_names}"
            )
        return spec
    if isinstance(spec, str):
        kind, sep, num = spec.partition(":")
        if kind != "tp" or not sep or not num.isdigit():
            raise ValueError(
                f"unrecognized serving mesh spec {spec!r}; expected "
                f"'tp:N', an int, or a jax.sharding.Mesh"
            )
        n = int(num)
    else:
        n = int(spec)
    if n < 1:
        raise ValueError(f"serving mesh needs >= 1 device; got tp:{n}")
    devs = devices if devices is not None else jax.devices()
    if n > len(devs):
        raise ValueError(
            f"serving mesh 'tp:{n}' needs {n} devices but only "
            f"{len(devs)} are available — shrink the mesh or run on a "
            f"host with more devices"
        )
    return Mesh(np.array(devs[:n]), (axis_name,))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_batch(batch: dict, mesh: Mesh, axis: str = "data"):
    """Place a host batch dict on the mesh, split along the leading dim."""
    sh = batch_sharding(mesh, axis)
    return {k: jax.device_put(v, sh) for k, v in batch.items()}


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the mesh."""
    sh = replicated_sharding(mesh)
    return jax.device_put(tree, sh)


def host_gather(tree):
    """Make every array leaf host-fetchable. In multi-controller runs a
    leaf sharded across processes spans non-addressable devices and
    ``np.asarray`` refuses it; such leaves are all-gathered to a full
    host array first (fully-replicated leaves fetch directly even when
    their device set spans processes)."""

    def fix(x):
        if not isinstance(x, jax.Array):
            return x
        if x.is_fully_addressable or x.sharding.is_fully_replicated:
            return x
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(x, tiled=True)

    return jax.tree.map(fix, tree)


def zero_leaf_sharding(mesh: Mesh, leaf, axis: str = "data") -> NamedSharding:
    """ZeRO-1 placement rule for one optimizer-state leaf: shard the
    FIRST dimension divisible by the ``axis`` size; leaves with no such
    dimension (scalars, small biases) replicate. Params stay replicated —
    sharding only the moments means the update math runs on each rank's
    slice and XLA inserts one all-gather per parameter per step to
    rebuild the replicated p_new (the classic ZeRO-1 collective), cutting
    per-device optimizer memory ~axis-size-fold."""
    n = mesh.shape[axis]
    shape = getattr(leaf, "shape", ())
    for i, d in enumerate(shape):
        if d % n == 0 and d >= n:
            spec = [None] * len(shape)
            spec[i] = axis
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def shard_opt_state_zero(opt_state, mesh: Mesh, axis: str = "data"):
    """Place an optimizer-state pytree with ZeRO-1 shardings
    (``zero_leaf_sharding`` per leaf)."""
    return jax.tree.map(
        lambda x: jax.device_put(x, zero_leaf_sharding(mesh, x, axis)),
        opt_state,
    )
