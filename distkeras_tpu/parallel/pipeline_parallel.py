"""Pipeline parallelism — GPipe-style microbatching over a TPU mesh axis.

No reference counterpart (SURVEY §3.3: the reference has no model sharding
of any kind — the model must fit one worker); this module is a TPU-rebuild
capability, built the pjit-era way rather than as a port of GPipe's
device-placement code:

- the pipelined body must be a stack of HOMOGENEOUS blocks (the rebuild's
  ``TransformerBlock`` tower): per-block params are stacked on a leading
  "stage" axis and sharded across the mesh's ``"pipe"`` axis, so each
  device holds ``depth / S`` blocks — model memory scales 1/S;
- inside ``shard_map``, every device runs the same compiled program: at
  tick t it applies its blocks to the microbatch it holds, then passes the
  activation one hop down the ring via ``lax.ppermute``. After
  ``num_micro + S - 1`` ticks every microbatch has traversed every stage
  (the classic GPipe schedule, bubble fraction (S-1)/(M+S-1));
- the last stage's outputs are recovered with a masked ``psum`` (each
  device contributes only the outputs it produced as the final stage), so
  the result returns replicated and the whole thing — schedule, ring,
  recovery — is ONE differentiable XLA program: gradients flow back
  through the ppermute ring in reverse (its transpose is the reverse
  permutation), which is exactly backward pipelining.

Numerical contract: identical math to applying the block tower to each
microbatch sequentially — pinned by tests/test_pipeline_parallel.py
against the dense model, values and gradients.
"""

from __future__ import annotations

import functools

import jax

from distkeras_tpu.parallel.mesh import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_block_params(block_params: list):
    """List of per-block param pytrees (same structure) -> one pytree with a
    leading block axis, ready to shard over ``"pipe"``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *block_params)


def unstack_block_params(stacked) -> list:
    """Inverse of :func:`stack_block_params`."""
    depth = jax.tree.leaves(stacked)[0].shape[0]
    return [jax.tree.map(lambda a: a[i], stacked) for i in range(depth)]


def _stage_apply(stage_params, h, block_apply):
    """Apply this device's ``depth/S`` blocks in sequence (scan over the
    local block axis)."""

    def body(carry, params_i):
        return block_apply(params_i, carry), None

    out, _ = jax.lax.scan(body, h, stage_params)
    return out


def _pipeline_local(stage_params, x_micro, block_apply, axis_name, axis_size,
                    num_micro):
    """Per-device GPipe schedule (runs under shard_map).

    stage_params: this stage's (depth/S, ...) block stack — shard_map hands
    each device its slice of the leading block axis WITHOUT squeezing, and
    ``_stage_apply`` scans over those depth/S local blocks.
    x_micro: (M, mb, ...) microbatches — replicated input.
    Returns (M, mb, ...) outputs, replicated via masked psum.
    """
    stage = jax.lax.axis_index(axis_name)
    ticks = num_micro + axis_size - 1
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    mb_shape = x_micro.shape[1:]

    def tick(carry, t):
        h, out = carry
        # stage 0 injects microbatch t (other stages use what arrived)
        inject = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.minimum(t, num_micro - 1), axis=0, keepdims=False
        )
        h = jnp.where(stage == 0, inject, h)
        h_next = _stage_apply(stage_params, h, block_apply)
        # last stage finished microbatch (t - S + 1) at tick t
        done_idx = t - (axis_size - 1)
        is_done = jnp.logical_and(stage == axis_size - 1, done_idx >= 0)
        updated = jax.lax.dynamic_update_index_in_dim(
            out, h_next, jnp.maximum(done_idx, 0), axis=0
        )
        out = jnp.where(is_done, updated, out)
        h_next = jax.lax.ppermute(h_next, axis_name, perm)
        return (h_next, out), None

    # adding 0*stage marks the carries as varying over the pipe axis, and
    # 0*x_micro[0] picks up whatever OTHER manual axes the input varies
    # over (the "data" axis under 2-D pipeline x data sharding) — scan
    # requires carry-in/out types, including manual-axis variance, to match
    vary = (stage * 0).astype(x_micro.dtype) + x_micro[0] * 0
    h0 = jnp.zeros(mb_shape, x_micro.dtype) + vary
    out0 = jnp.zeros((num_micro, *mb_shape), x_micro.dtype) + vary[None]
    (_, out), _ = jax.lax.scan(tick, (h0, out0), jnp.arange(ticks))
    # only the last stage holds real outputs; psum over the axis recovers
    # them replicated (other stages contribute zeros)
    out = jnp.where(stage == axis_size - 1, out, jnp.zeros_like(out))
    return jax.lax.psum(out, axis_name)


def pipeline_apply(stacked_params, x, block_apply, mesh: Mesh,
                   axis_name: str = "pipe", num_micro: int | None = None,
                   batch_axis: str | None = None, param_specs=None):
    """Run ``x`` through the stacked block tower, pipelined over the mesh.

    stacked_params: pytree with leading block axis ``depth`` (depth must be
    divisible by the mesh axis size S; each stage runs depth/S blocks).
    x: (batch, ...) — batch must be divisible by ``num_micro``.
    block_apply: ``block_apply(one_block_params, h) -> h`` pure function.
    Returns (batch, ...) with the same values as applying the blocks
    sequentially (GPipe is an execution schedule, not an approximation).

    ``batch_axis``: optional mesh axis the MICROBATCH dim is sharded over
    (2-D pipeline x data parallelism): each data slice runs its own GPipe
    ring over ``axis_name`` on its batch shard — the schedule body is
    unchanged; only the specs keep the shards in place.

    ``param_specs``: optional pytree of ``PartitionSpec`` matching
    ``stacked_params`` for 3-D composition (pipeline x data x tensor):
    every spec must lead with ``axis_name`` (the block axis stays
    pipeline-sharded) and may shard trailing weight dims over a tensor
    axis — ``block_apply`` then sees LOCAL weight shards and owns the
    matching collectives (e.g. the Megatron pattern: column-shard w_in,
    row-shard w_out, ``lax.psum`` over the tensor axis after w_out).
    Default: every leaf ``P(axis_name)`` (weights replicated over all
    other axes).
    """
    axis_size = mesh.shape[axis_name]
    depth = jax.tree.leaves(stacked_params)[0].shape[0]
    if depth % axis_size:
        raise ValueError(
            f"block depth {depth} not divisible by mesh axis "
            f"{axis_name}={axis_size}"
        )
    num_micro = int(num_micro or axis_size)
    batch = x.shape[0]
    if batch % num_micro:
        raise ValueError(
            f"batch {batch} not divisible by num_micro={num_micro}"
        )
    mb = batch // num_micro
    if batch_axis is not None and mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch {mb} not divisible by mesh axis "
            f"{batch_axis}={mesh.shape[batch_axis]}"
        )
    x_micro = x.reshape(num_micro, mb, *x.shape[1:])

    # params: leading block axis sharded over "pipe" (replicated over any
    # data axis); input microbatches shard over batch_axis when given
    if param_specs is None:
        param_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    else:
        for spec in jax.tree.leaves(
            param_specs, is_leaf=lambda s: isinstance(s, P)
        ):
            if not spec or spec[0] != axis_name:
                raise ValueError(
                    f"param_specs must lead with the {axis_name!r} block "
                    f"axis, got {spec}"
                )
        param_spec = param_specs
    x_spec = P(None, batch_axis)
    fn = shard_map(
        functools.partial(
            _pipeline_local,
            block_apply=block_apply,
            axis_name=axis_name,
            axis_size=axis_size,
            num_micro=num_micro,
        ),
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
    )
    out = fn(stacked_params, x_micro)
    return out.reshape(batch, *out.shape[2:])


def shard_stacked_params(stacked_params, mesh: Mesh, axis_name: str = "pipe",
                         param_specs=None):
    """Place a stacked block pytree with its leading axis sharded over the
    pipeline mesh axis (device i holds blocks [i*depth/S, (i+1)*depth/S)).
    ``param_specs`` optionally gives per-leaf specs (3-D composition — see
    :func:`pipeline_apply`)."""
    if param_specs is None:
        sharding = NamedSharding(mesh, P(axis_name))
        return jax.tree.map(
            lambda a: jax.device_put(a, sharding), stacked_params
        )
    return jax.tree.map(
        lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec)),
        stacked_params,
        param_specs,
    )
