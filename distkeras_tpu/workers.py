"""Worker runtime: the per-chip training loops.

TPU-native rebuild of the reference's executor-side workers (reference:
distkeras/workers.py -> Worker / SingleTrainerWorker / DOWNPOURWorker /
AEASGDWorker / EAMSGDWorker / ADAGWorker / DynSGDWorker). The Keras
``train_on_batch`` hot loop becomes a jit-compiled ``lax.scan`` over a
*window* of W minibatches (the ``communication_window``): one XLA program
per window keeps the chip busy between host round-trips, which is the
TPU-shaped version of "train W batches between pull/commit".

Async workers split each window into ``begin_window`` (pull + launch device
compute) and ``finish_window`` (fetch result + commit) so that

- thread mode calls them back-to-back per worker thread (true asynchrony,
  one worker per chip), and
- the deterministic simulator interleaves begins/finishes across workers on
  a seeded schedule, reproducing staleness exactly (SURVEY §7.3: async
  semantics need a deterministic test harness).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distkeras_tpu.data.prefetch import Prefetcher
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.metrics import get_metric
from distkeras_tpu.utils.compression import maybe_decode_pull
from distkeras_tpu.utils.tree import host_copy, tree_scale, tree_sub


def _window_unroll(model) -> bool:
    """Whether this model's window scans should fully unroll.

    XLA:CPU executes CONVOLUTION-bearing ``while``-loop bodies ~33x slower
    than the identical ops compiled at top level (measured r5 on the
    north-star CNN window, 1 core: scan 11.1 vs unrolled 373.1 samples/sec;
    partial unroll keeps the loop and stays at ~10 — PERF.md r5). Dense
    models show the OPPOSITE trade: the config-1 MLP measured ~2x FASTER
    under the loop (1,226 vs 603 samples/sec) — so unroll only when a
    Conv2D is actually in the stack. Windows are small by design (default
    8 steps, the communication window), so full unroll costs bounded
    compile time. TPU always keeps the real loop: XLA:TPU loop bodies run
    at full speed, and unrolling would only bloat programs."""
    try:
        if jax.default_backend() != "cpu":
            return False
    except RuntimeError:  # backend not initialized yet: assume accelerator
        return False
    from distkeras_tpu.models.layers import Conv2D

    # _walk_layers (not a local re-walk): attribute-held conv sublayers in
    # composite layers must trigger the unroll too (r5 review finding)
    return any(isinstance(layer, Conv2D) for layer in _walk_layers(model))


# ---------------------------------------------------------------- core cache


def _walk_layers(model):
    """Every layer reachable from ``model`` — delegates to THE canonical
    traversal (``models.sequential.walk_layers``, driven by the
    ``Layer.sublayers()`` contract) rather than re-implementing one: a
    second walker with its own reachability heuristic would silently
    diverge on future composite layers (r5 review finding)."""
    from distkeras_tpu.models.sequential import walk_layers

    return walk_layers(getattr(model, "layers", None) or [])


# Process-local, trace-affecting layer hooks that ``get_config`` cannot
# see: ring/ulysses/flash attachment, the fused-layernorm kernel, and the
# MoE expert mesh. A model carrying ANY of these must bypass the core
# cache — and a cached donor that GROWS one must invalidate its entry —
# or same-config trainers silently trade compiled programs across hook
# states (r5 review findings, two rounds of them).
_RUNTIME_HOOK_ATTRS = ("attention_fn", "norm_fn", "mesh")


def _has_runtime_hooks(model) -> bool:
    return any(
        getattr(layer, attr, None) is not None
        for layer in _walk_layers(model)
        for attr in _RUNTIME_HOOK_ATTRS
    )


def _core_cache_key(model, optimizer_spec, loss, metrics, compute_dtype,
                    remat, accum_steps, aux_loss_weight):
    """Structural fingerprint of everything WorkerCore's compiled programs
    depend on — or None when the core is not safely cacheable (custom optax
    objects, callable losses/metrics, or models with runtime-attached
    attention hooks, which ``get_config`` cannot see)."""
    if optimizer_spec is None or not isinstance(loss, str):
        return None
    if not all(isinstance(m, str) for m in metrics):
        return None
    if getattr(model, "params", None) is None or not hasattr(model, "get_config"):
        return None
    if _has_runtime_hooks(model):
        return None
    import json

    try:
        cfg = json.dumps(model.get_config(), sort_keys=True, default=repr)
    except (TypeError, ValueError):
        return None
    try:
        backend = jax.default_backend()
    except RuntimeError:
        backend = "uninitialized"
    return (
        cfg,
        tuple(getattr(model, "input_shape", None) or ()),
        tuple(optimizer_spec),
        loss,
        tuple(metrics),
        compute_dtype,
        bool(remat),
        int(accum_steps),
        float(aux_loss_weight),
        backend,
    )


_CORE_CACHE: dict = {}
_CORE_CACHE_MAX = 32

# ------------------------------------------------------------------ core step


class WorkerCore:
    """Compiles the shared train/eval step functions for a model+optimizer.

    One core is shared by all workers of a trainer, so XLA compiles each
    program once per device; dispatch follows input placement.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        loss,
        metrics=("accuracy",),
        compute_dtype=None,
        remat=False,
        accum_steps=1,
        aux_loss_weight=0.01,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = get_loss(loss)
        self.metric_names = list(metrics)
        self.metric_fns = [get_metric(m) for m in metrics]
        self.compute_dtype = compute_dtype
        self.remat = bool(remat)
        # gradient accumulation: each optimizer step runs its batch as
        # accum_steps sequential microbatches (inner lax.scan), averaging
        # gradients — ~k x less activation memory at full-batch numerics
        # (BatchNorm running stats update per microbatch, the standard
        # grad-accum semantics)
        self.accum_steps = int(accum_steps)
        self.aux_loss_weight = float(aux_loss_weight)

        # platform/model-dependent window-scan unroll (see _window_unroll);
        # decided once here, host-side, after the backend is pinned
        unroll = _window_unroll(model)

        def _wscan(f, init, xs):
            return jax.lax.scan(f, init, xs, unroll=unroll or 1)

        model_apply = model.apply
        loss_fn = self.loss_fn
        metric_fns = self.metric_fns
        cdtype = compute_dtype
        aux_w = self.aux_loss_weight

        def train_fwd(params, state, rng, x):
            return model_apply(params, state, x, train=True, rng=rng)

        if remat:
            # rematerialize activations in the backward pass: trades MXU
            # FLOPs for HBM — lets bigger models / windows fit per chip
            train_fwd = jax.checkpoint(train_fwd)

        def compute_loss(params, state, rng, x, y):
            if cdtype is not None:
                x = x.astype(cdtype)
            y_pred, new_state = train_fwd(params, state, rng, x)
            y_pred = y_pred.astype(jnp.float32)
            # layers that emit regularizers through state (MoE routing's
            # load-balance loss) contribute aux_w * sum of "aux_loss" leaves;
            # constant-folded away for models without any
            loss = loss_fn(y_pred, y) + aux_w * _collect_aux_losses(new_state)
            return loss, (new_state, y_pred)

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

        # fused-apply optimizers (ops/pallas_kernels.py) compute new params
        # in one kernel pass; otherwise the standard optax two-step applies
        if hasattr(optimizer, "fused_apply"):
            def apply_opt(params, grads, opt_state):
                return optimizer.fused_apply(params, grads, opt_state)
        else:
            def apply_opt(params, grads, opt_state):
                updates, opt_state = optimizer.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state

        accum = self.accum_steps

        def batch_grads(params, state, sub, bx, by):
            """(loss, state, y_pred, grads) for one optimizer step — the
            whole batch at once, or accumulated over ``accum``
            microbatches (inner scan; grads averaged, so numerics match
            the full-batch step up to summation order)."""
            if accum == 1:
                (loss, (state, y_pred)), grads = grad_fn(
                    params, state, sub, bx, by
                )
                return loss, state, y_pred, grads
            b = bx.shape[0]
            xs_m = bx.reshape(accum, b // accum, *bx.shape[1:])
            ys_m = by.reshape(accum, b // accum, *by.shape[1:])
            subs = jax.random.split(sub, accum)

            def micro(carry, mb):
                state, gacc, lacc = carry
                (loss, (state, y_pred)), grads = grad_fn(
                    params, state, mb["r"], mb["x"], mb["y"]
                )
                gacc = jax.tree.map(jnp.add, gacc, grads)
                return (state, gacc, lacc + loss), y_pred

            g0 = jax.tree.map(jnp.zeros_like, params)
            # a REAL scan on purpose, never _wscan: unrolling here would
            # multiply — window_steps x accum_steps inlined conv graphs in
            # one CPU program (8 x 16 ResNet steps = hours of compile).
            # CPU conv accum pays the while-loop cost; bounded compile
            # beats the throughput win at this nesting (r5 review finding)
            (state, gacc, lsum), y_preds = jax.lax.scan(
                micro, (state, g0, jnp.float32(0.0)),
                {"x": xs_m, "y": ys_m, "r": subs},
            )
            grads = jax.tree.map(lambda g: g / accum, gacc)
            y_pred = y_preds.reshape(b, *y_preds.shape[2:])
            return lsum / accum, state, y_pred, grads

        def train_step(carry, batch):
            params, state, opt_state, rng = carry
            rng, sub = jax.random.split(rng)
            loss, state, y_pred, grads = batch_grads(
                params, state, sub, batch["x"], batch["y"]
            )
            params, opt_state = apply_opt(params, grads, opt_state)
            mets = {"loss": loss}
            for name, fn in zip(self.metric_names, metric_fns):
                mets[name] = fn(y_pred, batch["y"])
            return (params, state, opt_state, rng), mets

        def window(params, state, opt_state, rng, xs, ys):
            """Run a scan over W stacked minibatches; returns per-step metrics."""
            (params, state, opt_state, rng), mets = _wscan(
                train_step, (params, state, opt_state, rng), {"x": xs, "y": ys}
            )
            return params, state, opt_state, rng, mets

        def indexed_window(params, state, opt_state, rng, data_x, data_y, idx):
            """Device-resident window: the full dataset lives in HBM and each
            scan step gathers its minibatch by index (``idx``: (W, B) int32).
            The host ships ~4 bytes/sample of indices per window instead of
            the samples themselves, so steady-state throughput is
            compute-bound, not host-link-bound — the TPU-shaped answer to the
            reference's per-row Python iterator feed (reference:
            distkeras/workers.py -> SingleTrainerWorker minibatch assembly).
            Batch contents match the streamed path exactly for the same
            permutation, so trajectories are bit-identical either way."""

            def step(carry, ix):
                batch = {
                    "x": jnp.take(data_x, ix, axis=0),
                    "y": jnp.take(data_y, ix, axis=0),
                }
                return train_step(carry, batch)

            (params, state, opt_state, rng), mets = _wscan(
                step, (params, state, opt_state, rng), idx
            )
            return params, state, opt_state, rng, mets

        def grad_step(carry, batch):
            params, state, opt_state, rng, acc = carry
            rng, sub = jax.random.split(rng)
            loss, state, y_pred, grads = batch_grads(
                params, state, sub, batch["x"], batch["y"]
            )
            params, opt_state = apply_opt(params, grads, opt_state)
            acc = jax.tree.map(jnp.add, acc, grads)
            mets = {"loss": loss}
            for name, fn in zip(self.metric_names, metric_fns):
                mets[name] = fn(y_pred, batch["y"])
            return (params, state, opt_state, rng, acc), mets

        def grad_window(params, state, opt_state, rng, xs, ys):
            """Like window, but also accumulates raw gradients (ADAG)."""
            acc0 = jax.tree.map(jnp.zeros_like, params)
            (params, state, opt_state, rng, acc), mets = _wscan(
                grad_step, (params, state, opt_state, rng, acc0),
                {"x": xs, "y": ys},
            )
            return params, state, opt_state, rng, acc, mets

        def indexed_grad_window(params, state, opt_state, rng, data_x, data_y, idx):
            """grad_window over the device-resident feed: same contract as
            ``indexed_window`` (HBM-resident pool, (W, B) int32 gather per
            step), same accumulated-gradient output as ``grad_window`` —
            the resident path for the grad-committing async family (ADAG)."""

            def step(carry, ix):
                batch = {
                    "x": jnp.take(data_x, ix, axis=0),
                    "y": jnp.take(data_y, ix, axis=0),
                }
                return grad_step(carry, batch)

            acc0 = jax.tree.map(jnp.zeros_like, params)
            (params, state, opt_state, rng, acc), mets = _wscan(
                step, (params, state, opt_state, rng, acc0), idx
            )
            return params, state, opt_state, rng, acc, mets

        def eval_step(params, state, x, y):
            if cdtype is not None:
                x = x.astype(cdtype)
            y_pred, _ = model_apply(params, state, x, train=False)
            y_pred = y_pred.astype(jnp.float32)
            mets = {"loss": loss_fn(y_pred, y)}
            for name, fn in zip(self.metric_names, metric_fns):
                mets[name] = fn(y_pred, y)
            return mets

        self.window = jax.jit(window, donate_argnums=(0, 1, 2))
        self.indexed_window = jax.jit(indexed_window, donate_argnums=(0, 1, 2))
        self.grad_window = jax.jit(grad_window, donate_argnums=(0, 1, 2))
        self.indexed_grad_window = jax.jit(
            indexed_grad_window, donate_argnums=(0, 1, 2)
        )
        self.eval_step = jax.jit(eval_step)
        # unjitted handle for transform composition (the vmapped ensemble
        # jits vmap(window_fn) as ONE program over a stacked member axis)
        self.window_fn = window

    def init_opt_state(self, params):
        return self.optimizer.init(params)

    @classmethod
    def cached(
        cls,
        model,
        optimizer,
        loss,
        *,
        optimizer_spec=None,
        metrics=("accuracy",),
        compute_dtype=None,
        remat=False,
        accum_steps=1,
        aux_loss_weight=0.01,
    ):
        """A WorkerCore whose compiled programs are shared across every
        same-structure construction in the process.

        Constructing a trainer per round (the benchmark matrix's
        epochs-to-target loop; any user retuning in a notebook) used to
        re-trace and re-lower every window program each time — with the r5
        CPU conv-unroll (``_window_unroll``) that cost ~90 s/round on the
        1-core sandbox, dwarfing the actual training. Programs depend only
        on the model's STRUCTURE (apply is pure in params), the optimizer
        spec, loss/metrics names, and the dtype/remat/accum flags — the
        cache key (``_core_cache_key``); anything it cannot fingerprint
        (custom optax objects, callable losses, runtime-attached attention
        hooks) constructs an uncached core exactly as before. The returned
        core carries the CALLER's model object, so ``core.model.params``
        starts (SingleTrainerWorker with ``initial=None``) see the fresh
        weights, never a cache donor's."""
        import os

        key = (
            None
            if os.environ.get("DKT_DISABLE_CORE_CACHE")  # debug kill-switch
            else _core_cache_key(
                model, optimizer_spec, loss, metrics, compute_dtype, remat,
                accum_steps, aux_loss_weight,
            )
        )
        if key is None:
            return cls(
                model, optimizer, loss, metrics=metrics,
                compute_dtype=compute_dtype, remat=remat,
                accum_steps=accum_steps, aux_loss_weight=aux_loss_weight,
            )
        core = _CORE_CACHE.get(key)
        if core is not None:
            # the cached programs traced the donor model's apply; a runtime
            # hook grown SINCE caching would poison future retraces for
            # new shapes — drop the entry instead of trusting it
            if _has_runtime_hooks(core.model):
                del _CORE_CACHE[key]
            else:
                return core._rebound(model)
        # build the programs around a params-stripped structural shell of
        # the model (shared layer objects, no weight arrays): the closures
        # capture the donor's bound ``apply``, so caching a core built on
        # the caller's model would pin that model's full parameter arrays
        # for the cache entry's lifetime (r5 review finding). ``apply``
        # reads structure from ``self.layers`` and takes params explicitly,
        # so the shell traces identically.
        import copy

        shell = copy.copy(model)
        shell.params = None
        shell.state = None
        # model.predict() memoizes a jitted lambda that closes over the
        # DONOR model — carried into the shell it would pin the donor's
        # full parameter arrays, the exact leak the shell prevents
        shell.__dict__.pop("_predict_fn", None)
        core = cls(
            shell, optimizer, loss, metrics=metrics,
            compute_dtype=compute_dtype, remat=remat,
            accum_steps=accum_steps, aux_loss_weight=aux_loss_weight,
        )
        if len(_CORE_CACHE) >= _CORE_CACHE_MAX:  # FIFO bound
            _CORE_CACHE.pop(next(iter(_CORE_CACHE)))
        _CORE_CACHE[key] = core
        return core._rebound(model)

    def _rebound(self, model):
        """Shallow clone sharing the compiled programs, with ``model``
        swapped to the caller's instance (same architecture by key
        construction; ``apply`` is pure, so the traced programs transfer)."""
        import copy

        clone = copy.copy(self)
        clone.model = model
        return clone


def _metrics_to_records(mets) -> list:
    """Device metrics dict of (W,) arrays -> list of per-step float dicts."""
    host = {k: np.asarray(v) for k, v in mets.items()}
    w = len(next(iter(host.values())))
    return [{k: float(v[i]) for k, v in host.items()} for i in range(w)]


def state_leaf_name(path):
    """Name of a model-state pytree leaf from its tree_flatten_with_path
    path: the last path entry's key (dict trees — the model-state layout),
    else its string form. THE definition of which leaves count as
    "aux_loss", shared by the loss collection here and the trainer's
    worker-state aggregation policy."""
    if not path:
        return None
    last = path[-1]
    return last.key if hasattr(last, "key") else str(last)


def _collect_aux_losses(state):
    """Sum of every leaf named "aux_loss" in a model-state pytree — the
    channel layers use to surface differentiable regularizers (MoE's
    switch load-balance loss) to the training loss."""
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if state_leaf_name(path) == "aux_loss":
            total = total + jnp.sum(leaf).astype(jnp.float32)
    return total


def stack_window(batches: list, features_col: str, label_col: str):
    """List of W batch dicts -> stacked (W, B, ...) arrays."""
    xs = np.stack([b[features_col] for b in batches])
    ys = np.stack([b[label_col] for b in batches])
    return xs, ys


def iter_windows(dataset, batch_size: int, columns: list, window: int):
    """Group a dataset's batches into window-sized lists, flushing the
    ragged remainder window at the end — THE windowing semantics for every
    windowed trainer (SingleTrainerWorker and Trainer._windowed_epochs both
    route through here so they cannot diverge)."""
    pend = []
    for batch in dataset.batches(batch_size, columns=columns):
        pend.append(batch)
        if len(pend) == window:
            yield pend
            pend = []
    if pend:
        yield pend


def epoch_index_windows(n, batch_size, window, shuffle_seed, epoch):
    """(W, B) int32 index matrices for one epoch of device-resident training.

    THE single encoding of the resident paths' batch-assembly contract: the
    row order is exactly ``Dataset.shuffle(seed + epoch)``'s permutation
    (``np.random.default_rng`` — data/dataset.py), batches cut sequentially,
    remainder rows dropped (``Dataset.batches`` drop_remainder semantics).
    Both SingleTrainerWorker and the sync-DP trainer route through here, so
    the bit-identity guarantee against the streamed path cannot diverge
    between them."""
    perm = (
        np.random.default_rng(shuffle_seed + epoch).permutation(n)
        if shuffle_seed is not None
        else np.arange(n)
    )
    nb = n // batch_size
    idx_all = perm[: nb * batch_size].astype(np.int32).reshape(nb, batch_size)
    for w0 in range(0, nb, window):
        yield idx_all[w0 : w0 + window]


def resident_arrays(dataset, features_col, label_col):
    """Materialize the two training columns for HBM residency, with a clear
    boundary error for datasets that cannot be indexed by column (e.g.
    StreamingDataset, which exists precisely for data that does NOT fit in
    memory — stream those with device_resident=False)."""
    try:
        return (
            np.asarray(dataset[features_col]),
            np.asarray(dataset[label_col]),
        )
    except TypeError as exc:
        raise TypeError(
            "device_resident=True requires an in-memory Dataset whose "
            f"columns can be materialized; got {type(dataset).__name__}. "
            "Use device_resident=False to stream it."
        ) from exc


# --------------------------------------------------------------- sync workers


class SingleTrainerWorker:
    """Sequential minibatch loop on one device (reference:
    distkeras/workers.py -> SingleTrainerWorker.train)."""

    def __init__(self, core: WorkerCore, features_col, label_col, seed=0, device=None):
        self.core = core
        self.features_col = features_col
        self.label_col = label_col
        self.rng = jax.random.PRNGKey(seed)
        self.device = device
        # (samples, dispatch-to-dispatch seconds) per window; at steady state
        # dispatch time tracks device time via queue backpressure
        self.timings = []

    def train(
        self,
        dataset,
        batch_size,
        num_epoch=1,
        window=8,
        shuffle_seed=None,
        initial=None,
        initial_full=None,
        start_epoch=0,
        on_epoch_end=None,
        prefetch=2,
        device_resident=False,
    ):
        """``initial``: optional (params, state) to start from instead of the
        core model's (lets many workers share one compiled core).
        ``initial_full``: optional (params, state, opt_state, rng) — the full
        restore point a checkpoint resume supplies; with ``start_epoch`` this
        makes the continuation bit-identical to an uninterrupted run.
        ``on_epoch_end(epoch, params, state, opt_state, rng)``: checkpoint
        hook, called after each epoch's last window.
        ``prefetch``: windows staged (stack + device_put) by a background
        thread while the device computes the previous window — double
        buffering; 0 restores the synchronous input path. Window order is
        preserved either way, so results are bit-identical.
        ``device_resident``: ship the whole dataset to HBM once and drive
        ``WorkerCore.indexed_window`` with per-epoch shuffled index matrices
        instead of streaming sample windows from the host. Same permutation,
        same batch contents — trajectories stay bit-identical with the
        streamed path — but the per-window host traffic drops from the
        samples themselves to 4 bytes/sample of indices."""
        if initial_full is not None:
            params, state, opt_state, rng = (
                host_copy(initial_full[0]),
                host_copy(initial_full[1]),
                initial_full[2],
                initial_full[3],
            )
        else:
            if initial is not None:
                params, state = host_copy(initial[0]), host_copy(initial[1])
            else:
                params = host_copy(self.core.model.params)
                state = host_copy(self.core.model.state)
            opt_state = self.core.init_opt_state(params)
            rng = self.rng
        if self.device is not None:
            params, state, opt_state = jax.device_put(
                (params, state, opt_state), self.device
            )
        if device_resident:
            return self._train_resident(
                dataset,
                batch_size,
                num_epoch,
                window,
                shuffle_seed,
                params,
                state,
                opt_state,
                rng,
                start_epoch,
                on_epoch_end,
            )

        records = []
        cols = [self.features_col, self.label_col]

        for epoch in range(start_epoch, num_epoch):
            ds = (
                dataset.shuffle(shuffle_seed + epoch)
                if shuffle_seed is not None
                else dataset
            )
            with Prefetcher(
                iter_windows(ds, batch_size, cols, window),
                self._stage_window,
                depth=prefetch,
            ) as staged:
                for xs, ys in staged:
                    params, state, opt_state, rng, records_w = self._run(
                        params, state, opt_state, rng, xs, ys
                    )
                    records.extend(records_w)
            if on_epoch_end is not None:
                on_epoch_end(epoch, params, state, opt_state, rng)
        return params, state, records

    def _train_resident(
        self,
        dataset,
        batch_size,
        num_epoch,
        window,
        shuffle_seed,
        params,
        state,
        opt_state,
        rng,
        start_epoch,
        on_epoch_end,
    ):
        """Device-resident epoch loop: dataset in HBM, indices from the host.

        Batch assembly mirrors the streamed path exactly — per epoch the same
        ``default_rng(seed + epoch).permutation`` order, batches cut
        sequentially, remainder rows dropped (``Dataset.batches``
        drop_remainder semantics) — so the two paths produce bit-identical
        parameter trajectories."""
        n = len(dataset)
        data_x, data_y = resident_arrays(dataset, self.features_col, self.label_col)
        if n // batch_size > 0:  # don't ship a dataset no window will touch
            if self.device is not None:
                data_x, data_y = jax.device_put((data_x, data_y), self.device)
            else:
                data_x, data_y = jax.device_put((data_x, data_y))

        records = []
        for epoch in range(start_epoch, num_epoch):
            for idx in epoch_index_windows(
                n, batch_size, window, shuffle_seed, epoch
            ):
                t0 = time.perf_counter()
                params, state, opt_state, rng, mets = self.core.indexed_window(
                    params, state, opt_state, rng, data_x, data_y, idx
                )
                records_w = _metrics_to_records(mets)
                self.timings.append((idx.size, time.perf_counter() - t0))
                records.extend(records_w)
            if on_epoch_end is not None:
                on_epoch_end(epoch, params, state, opt_state, rng)
        return params, state, records

    def _stage_window(self, batches):
        """Host-side window prep (runs on the prefetch thread): stack the W
        batch dicts and ship the buffers to the device ahead of compute."""
        xs, ys = stack_window(batches, self.features_col, self.label_col)
        if self.device is not None:
            xs, ys = jax.device_put((xs, ys), self.device)
        return xs, ys

    def _run(self, params, state, opt_state, rng, xs, ys):
        t0 = time.perf_counter()
        params, state, opt_state, rng, mets = self.core.window(
            params, state, opt_state, rng, xs, ys
        )
        records = _metrics_to_records(mets)  # forces mets -> window finished
        self.timings.append((xs.shape[0] * xs.shape[1], time.perf_counter() - t0))
        return params, state, opt_state, rng, records


# -------------------------------------------------------------- async workers


class AsyncWorker:
    """Base async worker: owns one partition, one device, one PS connection.

    Lifecycle per window (reference: distkeras/workers.py -> NetworkWorker
    pull/commit cadence):
      begin_window(batches): pull from PS (algorithm-specific), launch the
        compiled window on the device (dispatch is async — the chip computes
        while the host thread yields);
      finish_window(): block on the result, compute the delta, commit.
    """

    uses_grad_window = False

    def __init__(
        self,
        core: WorkerCore,
        ps,
        worker_id: int,
        features_col,
        label_col,
        communication_window: int,
        seed=0,
        device=None,
        compress=None,
    ):
        self.core = core
        self.ps = ps
        self.worker_id = worker_id
        self.features_col = features_col
        self.label_col = label_col
        self.window_size = int(communication_window)
        from distkeras_tpu.utils.compression import parse_compress_spec

        # kinds: None | "int8" | "topk" (frac rides the spec string,
        # e.g. "topk:0.05" — see utils/compression.parse_compress_spec)
        self._compress_kind, self._compress_frac = parse_compress_spec(compress)
        self.compress = compress
        self._q_residual = None  # error-feedback state (utils/compression)
        self._rng0 = jax.random.fold_in(jax.random.PRNGKey(seed), worker_id)
        self.rng = self._rng0
        self.device = device
        self.records = []
        self.timings = []  # (samples, begin->commit seconds) per window
        self._seq = 0  # per-worker commit sequence (exactly-once at the PS)
        self._start_seq = 0  # windows to skip on resume (already absorbed)
        # persistent local slots
        self._params = None
        self._state = None
        self._opt_state = None
        self._pending = None
        # checkpoint/resume of worker-LOCAL state (VERDICT r2 weak #4):
        # when the trainer checkpoints, commits also hand host copies of
        # this worker's replica params (persistent for the elastic
        # algorithms), model state, optimizer moments, rng, and seq to the
        # PS, which stores them in the commit's locked section — the
        # restored system is then a reachable configuration of the async
        # execution, not a center with amnesiac workers. Each handoff costs
        # a device-to-host copy of params+opt_state; snapshot_stride > 1
        # amortizes it (a restored worker then replays at most stride-1
        # windows, which the PS dedup absorbs — "behind" is always safe).
        self.keep_snapshot = False
        self.snapshot_stride = 1
        self._snap = None  # latest committed local state (host copies)
        self._restore_point = None  # snapshot adopted at resume, if any
        # device-resident feed (stage_resident): partition pool in HBM
        self._resident = None
        self._resident_n = 0

    @property
    def ps_failovers(self) -> int:
        """How many times this worker's PS client rotated endpoints (0 for
        in-process / single-endpoint PS connections) — the per-worker face
        of the replicated-PS failover ledger."""
        return int(getattr(self.ps, "failovers", 0))

    def reset_for_retry(self, retry=None):
        """Restart this worker's training after a failure: from its resume
        restore point when it has one, else from scratch.

        From scratch, the commit sequence restarts at 0: the PS has already
        absorbed seqs 0..k, so the re-run's first k+1 commits are
        deduplicated — the retry cannot double-apply work (the reference's
        Spark-retry double-absorb weakness, SURVEY §5.3). After a resume the
        scratch seqs may predate the restored dedup table's window, so the
        retry goes back to the restore point instead. The replay's dedup
        holds across a PS FAILOVER too: the promoted standby's dedup table
        rode the replication stream, so a worker retry that lands on the
        new primary still cannot double-apply pre-crash windows.

        ``retry``: optional ``networking.RetryPolicy`` for the PS redial —
        the shared backoff implementation (the serving client uses the
        same one), for the case where the PS host is itself mid-restart
        when this worker comes back. A remote PS client constructed with
        its own policy already redials under it, and a multi-endpoint
        client's redial rotates through the endpoint list, so the retry
        lands on whichever replica is serving."""
        self.records = []
        self.timings = []
        self._pending = None
        if self._restore_point is not None:
            self._adopt(self._restore_point)
        else:
            self.rng = self._rng0
            self._seq = 0
            self._start_seq = 0
            self._params = None
            self._state = None
            self._opt_state = None
            self._q_residual = None
        if hasattr(self.ps, "reconnect"):
            # a crashed socket stream may be desynced — always redial
            if retry is not None:
                retry.call(self.ps.reconnect)
            else:
                self.ps.reconnect()

    # -- worker-local checkpoint/resume --------------------------------------

    def restore_snapshot(self, snap):
        """Adopt a worker-local checkpoint (see ``keep_snapshot``): replica
        params, model state, optimizer moments, rng position, and commit
        sequence. ``train`` then skips the first ``seq`` windows of the
        partition stream — the ones whose commits the restored PS center
        already contains (same seeded shuffles, so the stream position is
        exact)."""
        self._restore_point = snap
        self._snap = snap  # checkpoints before the first post-resume commit
        self._adopt(snap)  # must still carry this worker's restored state

    def _adopt(self, snap):
        def put(tree):
            tree = host_copy(tree)  # owned copies: never donate the snapshot
            return (
                jax.device_put(tree, self.device)
                if self.device is not None
                else tree
            )

        self._params = put(snap["params"])
        self._state = put(snap["state"])
        self._opt_state = put(snap["opt_state"])
        self.rng = jnp.asarray(np.asarray(snap["rng"]))
        self._seq = int(snap["seq"])
        self._start_seq = int(snap["seq"])
        # residual stays host-side (commit-path state, never donated)
        self._q_residual = host_copy(snap.get("q_residual"))

    # -- algorithm hooks ----------------------------------------------------

    def on_pull(self, center, tag):
        """Set local params from the pulled center. Override per algorithm."""
        raise NotImplementedError

    def make_delta(self, pulled, result):
        """Compute (delta, tag) to commit. Override per algorithm."""
        raise NotImplementedError

    # -- window machinery ---------------------------------------------------

    def _ensure_initialized(self, center):
        if self._state is None:
            self._state = host_copy(self.core.model.state)
            if self.device is not None:
                self._state = jax.device_put(self._state, self.device)
        if self._opt_state is None:
            opt = self.core.init_opt_state(center)
            self._opt_state = (
                jax.device_put(opt, self.device) if self.device is not None else opt
            )

    def begin_window(self, batches):
        # owned host (numpy) copies; worker_id doubles as the PS heartbeat
        center_host, tag = self.ps.pull(worker_id=self.worker_id)
        center_host = maybe_decode_pull(center_host)
        center = (
            jax.device_put(center_host, self.device)
            if self.device is not None
            else center_host
        )
        self._ensure_initialized(center)
        self.on_pull(center, tag)
        xs, ys = stack_window(batches, self.features_col, self.label_col)
        if self.device is not None:
            xs, ys = jax.device_put((xs, ys), self.device)
        fn = self.core.grad_window if self.uses_grad_window else self.core.window
        out = fn(self._params, self._state, self._opt_state, self.rng, xs, ys)
        # keep the host copy for delta computation: the device-side center may
        # be donated by the window call through self._params
        self._pending = {
            "pulled": (center_host, tag),
            "out": out,
            "samples": xs.shape[0] * xs.shape[1],
            "t0": time.perf_counter(),
        }

    def warmup(self, part, batch_size, device_resident=False):
        """Compile this worker's window program before training starts, on
        throwaway state (the trainer's pre-thread warmup: without it every
        worker's first window dispatches into the XLA compile gap, pulls
        the identical initial center, and commits full deltas on top of
        each other — a maximal-staleness burst). Lives on the worker so
        the streamed/indexed program dispatch has exactly one owner
        (mirrors ``begin_window``/``begin_window_indexed``)."""
        batch = next(
            part.batches(
                batch_size, columns=[self.features_col, self.label_col]
            ),
            None,
        )
        if batch is None:  # partition smaller than one batch: nothing to warm
            return
        params = host_copy(self.core.model.params)
        state = host_copy(self.core.model.state)
        opt_state = self.core.init_opt_state(params)
        rng = jax.random.PRNGKey(0)
        if device_resident:
            # the compile keys on the staged pool's shape, so warm against
            # this worker's own pool (stage_resident dedups the re-stage
            # when train() runs)
            self.stage_resident(part)
            idx = np.zeros((self.window_size, batch_size), np.int32)
            fn = (
                self.core.indexed_grad_window
                if self.uses_grad_window
                else self.core.indexed_window
            )
            out = fn(params, state, opt_state, rng, *self._resident, idx)
        else:
            zeros = {k: np.zeros_like(v) for k, v in batch.items()}
            xs, ys = stack_window(
                [zeros] * self.window_size, self.features_col, self.label_col
            )
            fn = (
                self.core.grad_window
                if self.uses_grad_window
                else self.core.window
            )
            out = fn(params, state, opt_state, rng, xs, ys)
        jax.block_until_ready(out)

    def stage_resident(self, dataset):
        """Ship this worker's partition to device memory ONCE; subsequent
        windows stream only the (W, B) int32 index matrices
        (``begin_window_indexed``) — the async face of the device-resident
        input path (same 4-bytes/sample/window host-traffic contract as
        ``SingleTrainerWorker._train_resident``)."""
        if self._resident is not None and self._resident_n == len(dataset):
            return  # already staged (warmup or a retry): the pool is the same
        data_x, data_y = resident_arrays(
            dataset, self.features_col, self.label_col
        )
        self._resident_n = data_x.shape[0]
        if self.device is not None:
            self._resident = jax.device_put((data_x, data_y), self.device)
        else:
            self._resident = jax.device_put((data_x, data_y))

    def iter_index_windows(self, num_epoch, batch_size, shuffle_seed):
        """The resident twin of ``iter_window_batches``: (W, B) index
        matrices, one per commit, across all epochs. Routed through
        ``epoch_index_windows`` so the batch-assembly contract (and with it
        the resume-skip stream alignment) is bit-identical to the streamed
        window stream."""
        for epoch in range(num_epoch):
            yield from epoch_index_windows(
                self._resident_n, batch_size, self.window_size,
                shuffle_seed, epoch,
            )

    def begin_window_indexed(self, idx):
        """``begin_window`` over the device-resident pool: pull + launch,
        shipping only the index matrix for this window."""
        center_host, tag = self.ps.pull(worker_id=self.worker_id)
        center_host = maybe_decode_pull(center_host)
        center = (
            jax.device_put(center_host, self.device)
            if self.device is not None
            else center_host
        )
        self._ensure_initialized(center)
        self.on_pull(center, tag)
        data_x, data_y = self._resident
        samples = int(idx.size)
        if self.device is not None:
            idx = jax.device_put(np.ascontiguousarray(idx), self.device)
        fn = (
            self.core.indexed_grad_window
            if self.uses_grad_window
            else self.core.indexed_window
        )
        out = fn(
            self._params, self._state, self._opt_state, self.rng,
            data_x, data_y, idx,
        )
        self._pending = {
            "pulled": (center_host, tag),
            "out": out,
            "samples": samples,
            "t0": time.perf_counter(),
        }

    def finish_window(self):
        pend = self._pending
        self._pending = None
        if self.uses_grad_window:
            params, state, opt_state, rng, acc, mets = pend["out"]
            result = {"params": params, "grad_acc": acc}
        else:
            params, state, opt_state, rng, mets = pend["out"]
            result = {"params": params}
        self._params, self._state, self._opt_state, self.rng = (
            params,
            state,
            opt_state,
            rng,
        )
        self.records.extend(_metrics_to_records(mets))
        delta, tag = self.make_delta(pend["pulled"], result)
        delta_np = jax.tree.map(np.asarray, delta)
        if self._compress_kind is not None:
            from distkeras_tpu.utils.compression import (
                compress_with_feedback,
                is_compressed,
                is_topk,
                topk_compress_with_feedback,
            )

            # fold last window's compression error in, compress, keep the
            # new residual for the next commit (error feedback). Elastic
            # workers compress inside make_delta instead (the displacement
            # must match what they subtracted locally) and arrive here
            # already compressed. This runs BEFORE the snapshot below so a
            # checkpoint carries THIS commit's residual — a snapshot of the
            # pre-commit residual would make a resume re-apply the previous
            # window's error and drop this one's.
            if not (is_compressed(delta_np) or is_topk(delta_np)):
                if self._compress_kind == "topk":
                    delta_np, self._q_residual = topk_compress_with_feedback(
                        delta_np, self._q_residual, self._compress_frac
                    )
                else:
                    delta_np, self._q_residual = compress_with_feedback(
                        delta_np, self._q_residual
                    )
        local_snap = None
        if self.keep_snapshot and (self._seq + 1) % self.snapshot_stride == 0:
            # host copies of this commit's local state, handed to the PS so
            # it lands in the SAME locked section as the commit: a
            # checkpoint can then never hold a worker state that is ahead
            # of the center it is saved with (behind is safe — the
            # replayed windows dedup at the PS)
            local_snap = self._make_snap(self._seq + 1)
        self.ps.commit(
            delta_np,
            tag,
            commit_id=(self.worker_id, self._seq),
            local_snap=local_snap,
        )
        self._seq += 1
        self.timings.append(
            (pend["samples"], time.perf_counter() - pend["t0"])
        )
        if local_snap is not None:
            self._snap = local_snap

    def _make_snap(self, seq: int) -> dict:
        # host_copy, NOT np.asarray: asarray may alias device buffers on
        # CPU, and these trees are the next window call's DONATED inputs —
        # an aliased long-lived snapshot would be corrupted in place
        snap = {
            "params": host_copy(self._params),
            "state": host_copy(self._state),
            "opt_state": host_copy(self._opt_state),
            "rng": host_copy(self.rng),
            "seq": np.int64(seq),
        }
        if self._q_residual is not None:
            # error-feedback residual rides the snapshot: a resumed
            # compressed run keeps carrying the same quantization error
            snap["q_residual"] = host_copy(self._q_residual)
        return snap

    def final_snapshot(self):
        """Fresh host-copy snapshot of the worker's end-of-run state (the
        trainer's final checkpoint payload; called after threads join, so
        no window is in flight). None if the worker never initialized."""
        if self._params is None or self._opt_state is None:
            return self._snap  # restored-but-never-ran keeps its restore point
        return self._make_snap(self._seq)

    def iter_window_batches(self, dataset, batch_size, num_epoch, shuffle_seed):
        """The worker's window stream: lists of batches, one list per commit
        (full windows plus each epoch's ragged tail), across all epochs.
        Deterministic given the seed — resume skipping relies on that."""
        cols = [self.features_col, self.label_col]
        for epoch in range(num_epoch):
            ds = (
                dataset.shuffle(shuffle_seed + epoch)
                if shuffle_seed is not None
                else dataset
            )
            pend = []
            for batch in ds.batches(batch_size, columns=cols):
                pend.append(batch)
                if len(pend) == self.window_size:
                    yield pend
                    pend = []
            if pend:
                yield pend

    def train(self, dataset, batch_size, num_epoch=1, shuffle_seed=None,
              device_resident=False):
        """Thread-mode entry: run all windows of this worker's partition,
        skipping the first ``_start_seq`` after a resume (their commits are
        already in the restored center).

        ``device_resident``: ship the partition to HBM once and drive the
        indexed window programs with per-epoch index matrices. The window
        stream (same shuffles, same batch contents, same ragged tails) is
        bit-identical to the streamed path, so commit seqs — and with them
        resume skipping and PS dedup — stay aligned across the two modes."""
        if device_resident:
            self.stage_resident(dataset)
            for i, idx in enumerate(
                self.iter_index_windows(num_epoch, batch_size, shuffle_seed)
            ):
                if i < self._start_seq:
                    continue
                self.begin_window_indexed(idx)
                self.finish_window()
            return self.records
        for i, pend in enumerate(
            self.iter_window_batches(dataset, batch_size, num_epoch, shuffle_seed)
        ):
            if i < self._start_seq:
                continue
            self.begin_window(pend)
            self.finish_window()
        return self.records


class DOWNPOURWorker(AsyncWorker):
    """Pull center, run W local steps, commit the weight delta
    (reference: distkeras/workers.py -> DOWNPOURWorker)."""

    def on_pull(self, center, tag):
        self._params = center  # local replica restarts from the center

    def make_delta(self, pulled, result):
        center, tag = pulled
        delta = tree_sub(result["params"], center)
        return delta, tag


class ADAGWorker(AsyncWorker):
    """Accumulated Gradient Normalization (Hermans): run W local steps,
    commit -lr * (sum of gradients) / W (reference: distkeras/workers.py ->
    ADAGWorker; the PS adds the pre-normalized delta)."""

    uses_grad_window = True

    def __init__(self, *args, learning_rate=0.01, **kwargs):
        super().__init__(*args, **kwargs)
        self.learning_rate = float(learning_rate)

    def on_pull(self, center, tag):
        self._params = center

    def make_delta(self, pulled, result):
        scale = -self.learning_rate / float(self.window_size)
        return tree_scale(result["grad_acc"], scale), pulled[1]


class DynSGDWorker(DOWNPOURWorker):
    """DOWNPOUR cadence against the versioned PS: the pull tag (PS update
    counter) rides along with the commit so the server can scale by
    1/(staleness+1) (reference: distkeras/workers.py -> DynSGDWorker)."""


class AEASGDWorker(AsyncWorker):
    """Asynchronous Elastic Averaging SGD (Zhang et al.).

    The local replica persists across windows (it does NOT reset to the
    center). Every window: train W steps, then with elastic force
    e = rho * lr * (x_local - x_center): x_local -= e; commit(e)
    (reference: distkeras/workers.py -> AEASGDWorker; §4.3).
    """

    def __init__(self, *args, rho=5.0, learning_rate=0.01, **kwargs):
        super().__init__(*args, **kwargs)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def on_pull(self, center, tag):
        if self._params is None:
            self._params = center  # first window: adopt the center

    def make_delta(self, pulled, result):
        center, tag = pulled
        alpha = self.rho * self.learning_rate
        elastic = tree_scale(tree_sub(result["params"], center), alpha)
        if self._compress_kind is not None:
            # the elastic rule applies the displacement on BOTH sides
            # (x_local -= e, center += e); compress BEFORE the local
            # subtraction so both apply the identical reconstructed value —
            # error-feedback-style asymmetry (raw locally, reconstructed at
            # the PS) makes replica and center drift apart and diverges.
            # No residual is kept: the un-shipped remainder stays in
            # x_local and re-enters the next elastic difference, which is
            # its own feedback loop.
            from distkeras_tpu.utils.compression import (
                quantize_tree,
                topk_compress,
            )

            host = jax.tree.map(np.asarray, elastic)
            if self._compress_kind == "topk":
                payload, deq = topk_compress(host, self._compress_frac)
            else:
                payload, deq = quantize_tree(host)
            self._params = tree_sub(result["params"], deq)
            return payload, tag
        self._params = tree_sub(result["params"], elastic)
        return elastic, tag


class EAMSGDWorker(AEASGDWorker):
    """Elastic averaging with momentum: identical elastic rule; the momentum
    lives in the worker's local optimizer (the trainer builds it with
    Nesterov momentum — reference: distkeras/workers.py -> EAMSGDWorker)."""
